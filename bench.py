"""Headline benchmark: BERT pretraining throughput on one chip.

Mirrors the BASELINE.json north-star workload (GluonNLP
scripts/bert/run_pretraining.py): full pretraining step — embeddings, encoder
on flash attention, MLM+NSP heads, loss, grads, AdamW — compiled to one XLA
executable, bf16 activations/params with fp32 master weights.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": MFU/0.40}

Env knobs: MXTPU_BENCH_MODEL (bert_12_768_12|bert_24_1024_16),
MXTPU_BENCH_BATCH, MXTPU_BENCH_SEQ, MXTPU_BENCH_REMAT (1 = jax.checkpoint
per encoder layer, frees HBM for bigger batches), MXTPU_PEAK_TFLOPS
(per-chip bf16 peak, default by device kind).

Device-blind proxy mode (no TPU needed — the CI ``perf-proxy`` gate)::

    python bench.py --proxy                          # every SERVE_SPECS family
    python bench.py --proxy --families bert,lenet
    python bench.py --proxy --out PERF_PROXY.json    # (re-)bank the baseline
    python bench.py --proxy --families bert --check PERF_PROXY.json
    python bench.py --proxy --mesh-step              # + 8-forced-host-device
                                                     #   compiled mesh-step probe

``--proxy`` traces every serving family's compiled graphs on CPU, prices
them with ``analysis.hlo.cost`` (FLOPs/step, bytes/step, fusion counts —
deterministic functions of the graph), measures the host dispatch gap
around a few compiled predict calls via ``profiler.step_report``, and
emits one structured record per family. ``--check`` diffs the
deterministic metrics against a banked baseline with a tolerance gate
(default ±5%): regressions fail (rc=1), improvements warn so the
baseline gets re-banked. A perf regression is caught even when the
device bench is blind (rc=75 tunnel wedge, BENCH_r03-r05).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp


def _peak_tflops() -> float:
    """Per-chip bf16 peak for MFU accounting — the shared
    ``util.peak_tflops`` table (by device kind, public specs;
    MXTPU_PEAK_TFLOPS overrides), the same source the autotuner's
    roofline score and the goodput ledger's MFU headline read."""
    from incubator_mxnet_tpu.util import peak_tflops
    return peak_tflops()


_DEFAULT_MODEL = {"resnet": "resnet50_v1", "bert": "bert_12_768_12"}


def _bench_workload() -> str:
    """THE workload resolution main() uses, shared with the watchdog
    abort record so they can't drift."""
    return os.environ.get("MXTPU_BENCH_WORKLOAD", "bert")


def _bench_model(workload: str):
    """THE workload→model resolution main() uses, shared with the
    watchdog abort record so they can't drift. ssd/frcnn run the fixed
    in-tree model and ignore MXTPU_BENCH_MODEL (returns None)."""
    if workload not in _DEFAULT_MODEL:
        return None
    return os.environ.get("MXTPU_BENCH_MODEL", _DEFAULT_MODEL[workload])


def _watchdog_record(budget: int, attempts: int = 1) -> dict:
    """The structured abort record the watchdog prints as its last stdout
    line: harnesses that parse one-JSON-line-per-run see a machine-readable
    ``{"error": "device_init_timeout"}`` instead of ``parsed: null``, so a
    wedged TPU tunnel (rc=75, see BENCH_r05.json) is distinguishable from
    "produced no data". ``goodput: null`` rides along so the record is
    self-describing (no goodput data was measured this round);
    ``tools/perf_history.py`` classifies the round BLIND off the null
    ``value`` and renders the ``error`` as its reason instead of
    silently skipping it — a run of rc=75 wedges reads as "no device
    data since rN", never as "no regressions". ``attempts`` is the number
    of full watchdog windows waited (1 = no retry configured): a round
    that wedged through a retry is distinguishable from one that was
    never given a second window."""
    workload = _bench_workload()
    model = _bench_model(workload)
    return {
        "error": "device_init_timeout",
        "attempts": int(attempts),
        "goodput": None,
        "metric": None,
        "value": None,
        "unit": None,
        "vs_baseline": None,
        "extra": {"timeout_s": budget, "rc": 75, "workload": workload,
                  "model": model},
    }


class _BenchWatchdog:
    """The device-init watchdog with one bounded retry: a fired window
    re-arms up to ``MXTPU_BENCH_RETRIES`` times (default 1), each retry
    window stretched by ``MXTPU_BENCH_RETRY_BACKOFF_S`` (default 60) —
    a pool grant that lands late is a recovered round, not a blind one.
    Only after the LAST window expires does the abort record print
    (with the ``attempts`` count) and the process ``os._exit(75)``.

    The timer thread cannot un-wedge the blocked device-init call — the
    retry IS the extra bounded window; what it buys is distinguishing
    "wedged forever" from "slow grant", without a human re-launching.
    """

    def __init__(self, budget: int, retries: int, backoff_s: float):
        import threading
        self._threading = threading
        self._budget = budget
        self._retries = max(0, retries)
        self._backoff = max(0.0, backoff_s)
        self._lock = threading.Lock()
        self._attempt = 1
        self._cancelled = False
        self._timer = None
        self._arm(budget)

    def _arm(self, window: float) -> None:
        t = self._threading.Timer(window, self._fire)
        t.daemon = True
        self._timer = t
        t.start()

    def cancel(self) -> None:
        with self._lock:
            self._cancelled = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    @property
    def attempts(self) -> int:
        return self._attempt

    def _fire(self) -> None:
        import sys
        with self._lock:
            if self._cancelled:
                return
            attempt = self._attempt
            if attempt <= self._retries:
                # the bounded retry: one more window, stretched by the
                # backoff, and the round records that it needed it
                self._attempt = attempt + 1
                window = self._budget + self._backoff
                sys.stderr.write(
                    f"bench.py watchdog: no result within {self._budget}s "
                    f"(attempt {attempt}) — re-arming once with backoff: "
                    f"{window:g}s more before aborting.\n")
                sys.stderr.flush()
                self._arm(window)
                return
            attempts = self._attempt
        sys.stderr.write(
            f"bench.py watchdog: no result after {attempts} attempt(s) "
            f"({self._budget}s budget) — the TPU tunnel/device init is "
            "likely wedged; aborting.\n")
        sys.stderr.flush()
        # the one JSON line the bench harness parses: a structured abort
        # record, not silence
        sys.stdout.write(json.dumps(
            _watchdog_record(self._budget, attempts=attempts)) + "\n")
        sys.stdout.flush()
        os._exit(75)  # EX_TEMPFAIL


def _arm_watchdog():
    """Arm and return the watchdog (None when disabled) — callers cancel
    it once the device proves alive (see ``_measure``).

    Fail loudly instead of hanging forever if the TPU tunnel is wedged
    (device init blocks indefinitely when the pool grant is stuck).
    MXTPU_BENCH_TIMEOUT seconds, default 1500; 0 disables. One bounded
    retry with backoff before aborting (MXTPU_BENCH_RETRIES /
    MXTPU_BENCH_RETRY_BACKOFF_S; see :class:`_BenchWatchdog`).

    Uses a daemon timer + os._exit: a Python signal handler could never run
    while the main thread is blocked inside the C++ device-init call (the
    exact hang being guarded against).
    """
    budget = int(os.environ.get("MXTPU_BENCH_TIMEOUT", "1500"))
    if budget <= 0:
        return
    retries = int(os.environ.get("MXTPU_BENCH_RETRIES", "1"))
    backoff = float(os.environ.get("MXTPU_BENCH_RETRY_BACKOFF_S", "60"))
    return _BenchWatchdog(budget, retries, backoff)


# fwd GMACs per image at 224x224 (the canonical He-et-al. multiply-add
# counts); FLOPs = 2x MACs, train step ≈ 3x fwd, spatial cost scales with
# (img/224)^2
_RESNET_FWD_GMACS_224 = {"resnet18_v1": 1.82, "resnet34_v1": 3.67,
                         "resnet50_v1": 3.87, "resnet101_v1": 7.58,
                         "resnet50_v2": 4.10}


def _measure(trainer, batch, steps, watchdog):
    """The shared steady-state measurement protocol: compile step (watchdog
    armed), cancel watchdog once the device proved alive, pre-place resident
    inputs, warm, optional MXTPU_BENCH_TRACE profiled step, timed loop with
    one honest sync at the end. Returns (dt_seconds, final_loss)."""
    import jax

    trainer.step(*batch).asnumpy()  # init + compile
    if watchdog is not None:
        watchdog.cancel()           # device is alive; don't cap a long sweep
    batch = trainer.place(*batch)   # resident inputs: steady-state loop
    trainer.step(*batch).asnumpy()  # warm
    trace_dir = os.environ.get("MXTPU_BENCH_TRACE")
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            trainer.step(*batch).asnumpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(*batch)
    loss.asnumpy()
    return (time.perf_counter() - t0) / steps, loss


def run_resnet(watchdog) -> dict:
    """imgs/sec/chip on a model-zoo ResNet training step (BASELINE.md row:
    GluonCV train_imagenet.py counterpart). Synthetic NCHW batch; whole step
    (fwd, CE loss, grads, SGD-momentum) compiled to one XLA executable."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, parallel
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    model_name = _bench_model("resnet")
    if model_name not in _RESNET_FWD_GMACS_224:    # before any device work
        raise SystemExit(
            f"MXTPU_BENCH_MODEL={model_name!r} has no FLOP table entry; "
            f"choose one of {sorted(_RESNET_FWD_GMACS_224)}")
    B = int(os.environ.get("MXTPU_BENCH_BATCH", "32"))
    img = int(os.environ.get("MXTPU_BENCH_IMG", "224"))
    steps = int(os.environ.get("MXTPU_BENCH_STEPS", "20"))
    classes = 1000
    peak_tflops = _peak_tflops()

    net = vision.get_model(model_name, classes=classes)
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh(devices=jax.devices()[:1])
    trainer = parallel.ShardedTrainer(
        net, lambda out, label: ce(out, label), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9, "multi_precision": True},
        mesh=mesh, n_labels=1)

    rng = onp.random.RandomState(0)
    x = rng.randn(B, 3, img, img).astype(onp.float32)
    y = rng.randint(0, classes, (B,)).astype("float32")
    import jax.numpy as jnp
    dt, loss = _measure(trainer, (x.astype(jnp.bfloat16), y), steps, watchdog)

    imgs_per_sec = B / dt
    fwd_gmacs = _RESNET_FWD_GMACS_224[model_name] * (img / 224.0) ** 2
    flops = 3.0 * 2.0 * fwd_gmacs * 1e9 * B   # train = 3x fwd, FLOP = 2x MAC
    mfu = (flops / dt) / (peak_tflops * 1e12)
    return {
        "metric": f"{model_name}_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_ms": round(dt * 1e3, 2), "mfu": round(mfu, 4),
                  "batch": B, "img": img,
                  "backend": jax.default_backend(),
                  "loss": float(loss.asnumpy())},
    }


def _ssd_gmacs(img: int, num_classes: int,
               filters=(32, 64, 128, 128, 128),
               anchors_per_pos: int = 4) -> float:
    """Analytic fwd GMACs for the in-tree SSD (models/ssd.py): VGG-style
    trunk of two 3x3 convs per scale + per-scale cls/box heads."""
    macs = 0.0
    cin, s = 3, img
    feats = []
    for f in filters:
        macs += 9 * cin * f * s * s + 9 * f * f * s * s
        s //= 2
        feats.append((f, s))
        cin = f
    for f, sp in feats[1:]:   # heads run on all scales but the stem
        macs += 9 * f * (anchors_per_pos * (num_classes + 1)) * sp * sp
        macs += 9 * f * (anchors_per_pos * 4) * sp * sp
    return macs / 1e9


def run_ssd(watchdog) -> dict:
    """imgs/sec/chip on the SSD-300 training step (BASELINE.md row:
    GluonCV train_ssd.py counterpart; BASELINE.json configs[4]). Whole step
    — forward, MultiBoxTarget matching, CE+SmoothL1, grads, SGD-momentum —
    compiled to one XLA executable."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import models, parallel

    B = int(os.environ.get("MXTPU_BENCH_BATCH", "16"))
    img = int(os.environ.get("MXTPU_BENCH_IMG", "300"))
    steps = int(os.environ.get("MXTPU_BENCH_STEPS", "20"))
    classes = 20
    peak_tflops = _peak_tflops()

    net = models.SSD(num_classes=classes)
    net.initialize(mx.init.Xavier())
    loss = models.SSDTargetLoss()
    mesh = parallel.make_mesh(devices=jax.devices()[:1])
    trainer = parallel.ShardedTrainer(
        net, lambda out, label: loss(out[0], out[1], out[2], label), "sgd",
        {"learning_rate": 0.01, "momentum": 0.9}, mesh=mesh, n_labels=1)

    rng = onp.random.RandomState(0)
    x = rng.rand(B, 3, img, img).astype(onp.float32)
    lab = onp.zeros((B, 1, 5), onp.float32)
    lab[:, 0, 0] = rng.randint(0, classes, B)
    lab[:, 0, 1:3] = 0.2
    lab[:, 0, 3:5] = 0.7
    dt, lval = _measure(trainer, (x, lab), steps, watchdog)

    imgs_per_sec = B / dt
    flops = 3.0 * 2.0 * _ssd_gmacs(img, classes) * 1e9 * B
    mfu = (flops / dt) / (peak_tflops * 1e12)
    return {
        "metric": "ssd300_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_ms": round(dt * 1e3, 2), "mfu": round(mfu, 4),
                  "batch": B, "img": img,
                  "backend": jax.default_backend(),
                  "loss": float(lval.asnumpy())},
    }


def _frcnn_gmacs(img: int, filters=(32, 64, 128), A: int = 9, R: int = 128,
                 num_classes: int = 20, roi: int = 7, head: int = 128) -> float:
    """Analytic fwd GMACs for the in-tree Faster-RCNN (models/rcnn.py):
    one 3x3 conv per backbone scale, RPN conv + 1x1 heads, per-roi dense
    head over the ROIAlign crop."""
    macs = 0.0
    cin, s = 3, img
    for f in filters:
        macs += 9 * cin * f * s * s
        s //= 2
        cin = f
    f = filters[-1]
    macs += 9 * f * f * s * s                      # rpn trunk conv
    macs += f * (2 * A + 4 * A) * s * s            # rpn cls/reg 1x1
    C1 = num_classes + 1
    macs += R * (f * roi * roi * head + head * C1 + head * 4 * C1)
    return macs / 1e9


def run_frcnn(watchdog) -> dict:
    """imgs/sec/chip on the Faster-RCNN training step (BASELINE.md row:
    GluonCV train_faster_rcnn.py counterpart; BASELINE.json configs[4]
    names Faster-RCNN alongside SSD). Whole two-stage step — backbone, RPN,
    fixed-shape MultiProposal NMS scan, gt-append, ROIAlign, four-way
    AnchorTarget/ProposalTarget loss, grads, SGD-momentum — compiled to one
    XLA executable."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import models, parallel

    B = int(os.environ.get("MXTPU_BENCH_BATCH", "8"))
    img = int(os.environ.get("MXTPU_BENCH_IMG", "224"))
    steps = int(os.environ.get("MXTPU_BENCH_STEPS", "20"))
    classes = 20
    R = 128
    peak_tflops = _peak_tflops()

    net = models.FasterRCNN(
        num_classes=classes, scales=(4, 8, 16), ratios=(0.5, 1, 2),
        feature_stride=8, rpn_pre_nms_top_n=1000, rpn_post_nms_top_n=R,
        rpn_min_size=4, backbone_filters=(32, 64, 128), output_rpn=True)
    net.initialize(mx.init.Xavier())
    loss = models.FasterRCNNTargetLoss(
        num_classes=classes, scales=(4, 8, 16), ratios=(0.5, 1, 2),
        feature_stride=8)
    mesh = parallel.make_mesh(devices=jax.devices()[:1])
    trainer = parallel.ShardedTrainer(
        net, lambda out, gt, info: loss(out[0], out[1], out[2], out[3],
                                        out[4], gt, info),
        "sgd", {"learning_rate": 0.01, "momentum": 0.9}, mesh=mesh,
        n_labels=2)

    rng = onp.random.RandomState(0)
    x = rng.rand(B, 3, img, img).astype(onp.float32)
    gt = onp.full((B, 4, 5), -1.0, onp.float32)     # up to 4 boxes, padded
    for b in range(B):
        for m in range(rng.randint(1, 5)):
            w, h = rng.randint(img // 4, img // 2 + 1, 2)
            x0 = rng.randint(0, img - w)
            y0 = rng.randint(0, img - h)
            gt[b, m] = [rng.randint(0, classes), x0, y0,
                        x0 + w - 1, y0 + h - 1]
    info = onp.tile([img, img, 1.0], (B, 1)).astype(onp.float32)
    dt, lval = _measure(trainer, (x, info, gt, gt, info), steps, watchdog)

    imgs_per_sec = B / dt
    gmacs = _frcnn_gmacs(img, A=9, R=R + gt.shape[1], num_classes=classes)
    flops = 3.0 * 2.0 * gmacs * 1e9 * B
    mfu = (flops / dt) / (peak_tflops * 1e12)
    return {
        "metric": "frcnn_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_ms": round(dt * 1e3, 2), "mfu": round(mfu, 4),
                  "batch": B, "img": img, "rois": R,
                  "backend": jax.default_backend(),
                  "loss": float(lval.asnumpy())},
    }


# ---------------------------------------------------------------------------
# --proxy: device-blind perf proxy (trace + cost + host-gap, no TPU)
# ---------------------------------------------------------------------------

#: banked-baseline metrics the --check gate compares (deterministic
#: functions of the traced graph only — wall-time metrics like
#: host_gap_ms vary per machine and are reported, never gated).
#: graphs_per_step: jitted-executable invocations one steady-state
#: training step makes — the fused whole-step capture's contract is 1
#: (guard + optimizer + LR inside the one donated pjit step)
#: peak_live_bytes: the liveness-scan residency high-water mark — a
#: config that silently grows what must fit in HBM fails here even
#: though its traffic metrics look unchanged (the ZeRO-1 class of
#: regression)
_PROXY_GATE_KEYS = ("flops_per_step", "bytes_per_step",
                    "comm_bytes_per_step", "graphs_per_step",
                    "peak_live_bytes")
#: measured fields excluded from the banked file so re-banking on a
#: different machine never churns the committed baseline
_PROXY_VOLATILE_KEYS = ("host_gap_ms", "instrumented_pct",
                        "host_gap_ms_fused", "host_gap_ms_unfused",
                        "host_gap_delta_ms")


def _proxy_sync(out) -> None:
    """Block until a predict result is real (host sees the data)."""
    leaves = out if isinstance(out, (tuple, list)) else (out,)
    for leaf in leaves:
        if hasattr(leaf, "asnumpy"):
            leaf.asnumpy()


def _proxy_record(family: str, iters: int = 4) -> dict:
    """One structured proxy record for a ``models.SERVE_SPECS`` family:
    the cost table over every bucket graph (via ``models.hlo_smoke`` —
    the same entry the hlo-lint gate analyzes) plus a measured host-gap
    probe (compile the example bucket once, then ``iters`` steady-state
    predict calls attributed by ``profiler.step_report``)."""
    from incubator_mxnet_tpu import models, profiler, telemetry
    from incubator_mxnet_tpu.analysis import hlo

    smoke = models.hlo_smoke(family)
    cm = smoke["compiled"]
    rep = hlo.cost(cm, max_graphs=max(8, smoke["table"].num_buckets()))
    head = rep.head
    if head is None:
        raise RuntimeError(
            f"--proxy: family {family!r} traced zero graphs "
            f"(skipped: {rep.skipped}) — cannot price it")
    args = smoke["example_args"]
    _proxy_sync(cm.predict(*args))        # compile the example bucket
    profiler.reset_spans()
    for _ in range(iters):
        _proxy_sync(cm.predict(*args))
    sr = profiler.step_report(frame="serve.predict")
    record = {
        "graphs": len(rep.rows),
        "flops_per_step": rep.model_flops_per_step(),
        "bytes_per_step": rep.bytes_per_step(),
        "peak_live_bytes": rep.peak_live_bytes(),
        "ladder_peak_bytes": rep.ladder_peak_bytes(),
        "comm_bytes_per_step": rep.comm_bytes_per_step(),
        "collective_ops": rep.collective_ops_per_step(),
        "param_bytes": head.param_bytes,
        "activation_bytes": head.activation_bytes,
        "transcendentals": head.transcendentals,
        "eqns": head.eqns,
        "fusible_eqns": head.fusible_eqns,
        "fusion_groups": head.fusion_groups,
        "fusion_candidates": head.fusion_candidates,
        "unknown_eqns": head.unknown_eqns,
        "host_gap_ms": sr["host_gap_ms_mean"],
        "instrumented_pct": sr["instrumented_pct"],
    }
    telemetry.emit("perf.proxy", family=family, **record)
    return record


def _proxy_record_int8(family: str, iters: int = 4) -> dict:
    """One structured proxy record for a ``models.QUANT_FAMILIES``
    calibrated int8 twin (``models.quantized_smoke`` — the same entry the
    quant-lint gate analyzes). Same deterministic cost keys as
    :func:`_proxy_record` so ``_proxy_compare`` gates them identically,
    plus the deterministic ratios vs the f32 twin — the banked proof the
    quantization actually pays (bytes strictly below 1.0)."""
    from incubator_mxnet_tpu import models, profiler, telemetry
    from incubator_mxnet_tpu.analysis import hlo

    qsm = models.quantized_smoke(family)
    cm = qsm["compiled"]
    max_g = max(8, qsm["table"].num_buckets())
    rep = hlo.cost(cm, max_graphs=max_g)
    head = rep.head
    if head is None:
        raise RuntimeError(
            f"--proxy: int8 family {family!r} traced zero graphs "
            f"(skipped: {rep.skipped}) — cannot price it")
    f32 = qsm["f32"]["compiled"]
    f32_rep = hlo.cost(f32, max_graphs=max_g)
    args = qsm["example_args"]
    _proxy_sync(cm.predict(*args))        # compile the example bucket
    profiler.reset_spans()
    for _ in range(iters):
        _proxy_sync(cm.predict(*args))
    sr = profiler.step_report(frame="serve.predict")
    record = {
        "graphs": len(rep.rows),
        "flops_per_step": rep.model_flops_per_step(),
        "bytes_per_step": rep.bytes_per_step(),
        "peak_live_bytes": rep.peak_live_bytes(),
        "ladder_peak_bytes": rep.ladder_peak_bytes(),
        "comm_bytes_per_step": rep.comm_bytes_per_step(),
        "collective_ops": rep.collective_ops_per_step(),
        "param_bytes": head.param_bytes,
        "activation_bytes": head.activation_bytes,
        "transcendentals": head.transcendentals,
        "eqns": head.eqns,
        "fusible_eqns": head.fusible_eqns,
        "fusion_groups": head.fusion_groups,
        "fusion_candidates": head.fusion_candidates,
        "unknown_eqns": head.unknown_eqns,
        "bytes_ratio_vs_f32": (rep.bytes_per_step()
                               / max(f32_rep.bytes_per_step(), 1)),
        "ladder_peak_ratio_vs_f32": (rep.ladder_peak_bytes()
                                     / max(f32_rep.ladder_peak_bytes(), 1)),
        "host_gap_ms": sr["host_gap_ms_mean"],
        "instrumented_pct": sr["instrumented_pct"],
    }
    telemetry.emit("perf.proxy", family=family + "_int8", **record)
    return record


def _proxy_compare(current: dict, banked: dict, tol: float):
    """Gate the deterministic metrics against the banked baseline.
    Returns ``(failures, warnings)`` — a metric above ``1 + tol`` times
    the banked value is a regression (fail), below ``1 - tol`` an
    improvement (warn, so the baseline gets re-banked)."""
    failures, warnings = [], []
    for fam in sorted(current):
        rec, base = current[fam], banked.get(fam)
        if base is None:
            warnings.append(f"{fam}: no banked baseline — re-bank "
                            "PERF_PROXY.json (bench.py --proxy --out)")
            continue
        for key in _PROXY_GATE_KEYS:
            b, c = base.get(key), rec.get(key)
            if b is None or c is None:
                continue
            if not b:
                # a zero baseline has no ratio: any appearance IS the
                # regression (e.g. collectives sneaking into a
                # single-device serving graph, comm 0 -> N bytes)
                if c:
                    failures.append(
                        f"{fam}.{key}: {c:.6g} vs banked 0 — the metric "
                        "appeared from zero (new per-step cost)")
                continue
            ratio = c / b
            if ratio > 1.0 + tol:
                failures.append(
                    f"{fam}.{key}: {c:.6g} vs banked {b:.6g} "
                    f"(+{(ratio - 1) * 100:.1f}% > {tol * 100:.0f}% "
                    "tolerance) — the compiled graph got more expensive")
            elif ratio < 1.0 - tol:
                warnings.append(
                    f"{fam}.{key}: {c:.6g} vs banked {b:.6g} "
                    f"({(ratio - 1) * 100:.1f}%) — improvement; re-bank "
                    "the baseline (bench.py --proxy --out PERF_PROXY.json)")
    return failures, warnings


def _fused_step_record(steps: int = 6) -> dict:
    """Device-blind probe of whole-step capture: the SAME tiny guarded +
    LR-scheduled trainer stepped with the fused step (guard verdict +
    schedule position inside the one donated pjit graph — the default)
    and with ``MXTPU_FUSED_STEP=0`` (the before-capture shape: separate
    jitted finite check, per-step host LR eval + transfer). Banked
    metrics are deterministic — ``graphs_per_step`` (jitted-executable
    invocations per steady step: 1 fused vs 2 unfused) and the fused
    train graph's cost-table numbers; the measured host-gap delta is
    reported, never gated."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import fault, gluon, lr_scheduler, parallel, \
        profiler, telemetry
    from incubator_mxnet_tpu.analysis import hlo

    rng = onp.random.RandomState(0)
    x = rng.randn(16, 64).astype("float32")
    y = rng.randint(0, 8, (16,)).astype("float32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def probe(fused):
        prev = os.environ.get("MXTPU_FUSED_STEP")
        os.environ["MXTPU_FUSED_STEP"] = "1" if fused else "0"
        try:
            mx.random.seed(7)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Dense(128, activation="relu", in_units=64),
                    gluon.nn.Dense(8, in_units=128))
            net.initialize(mx.init.Xavier())
            tr = parallel.ShardedTrainer(
                net, loss_fn, "adamw",
                {"learning_rate": 1e-3,
                 "lr_scheduler": lr_scheduler.CosineScheduler(
                     max_update=1000, base_lr=1e-3)},
                mesh=parallel.make_mesh(devices=jax.devices()[:1]),
                guard=fault.StepGuard(policy="warn"))
            tr.step(x, y).asnumpy()        # init + compile
            batch = tr.place(x, y)         # steady state: resident inputs
            tr.step(*batch).asnumpy()      # warm
            profiler.reset_spans()
            for _ in range(steps):
                tr.step(*batch)
            sr = profiler.step_report(frame="step")
            return tr, sr
        finally:
            if prev is None:
                os.environ.pop("MXTPU_FUSED_STEP", None)
            else:
                os.environ["MXTPU_FUSED_STEP"] = prev

    tr_fused, sr_fused = probe(True)
    graphs_fused = tr_fused.last_step_graphs
    tr_unfused, sr_unfused = probe(False)
    graphs_unfused = tr_unfused.last_step_graphs
    rep = hlo.cost(tr_fused, sample_args=(x, y))
    gap_f = sr_fused["host_gap_ms_mean"]
    gap_u = sr_unfused["host_gap_ms_mean"]
    record = {
        "graphs": len(rep.rows),
        "graphs_per_step": graphs_fused,
        "graphs_per_step_unfused": graphs_unfused,
        "flops_per_step": rep.model_flops_per_step(),
        "bytes_per_step": rep.bytes_per_step(),
        "peak_live_bytes": rep.peak_live_bytes(),
        "comm_bytes_per_step": rep.comm_bytes_per_step(),
        "host_gap_ms_fused": gap_f,
        "host_gap_ms_unfused": gap_u,
        "host_gap_delta_ms": round(gap_u - gap_f, 4),
    }
    telemetry.emit("perf.proxy", family="fused_step", **record)
    return record


def _mesh_step_record(steps: int = 6) -> dict:
    """Device-blind probe of the compiled mesh training step on forced
    host devices: the SAME tiny model stepped on an 8-device dp×tp mesh
    (the default pjit path) and on one device, host dispatch gap measured
    by ``profiler.step_report`` over the trainer's own ``step`` frames,
    the mesh step graph priced by ``analysis.hlo.cost`` (collective verbs
    + comm bytes included). ``host_gap_ms_unsharded`` probes the PRE-pjit
    execution path — unsharded (one device), gradients through the
    per-parameter kvstore Python loop (``MXTPU_KVSTORE_FALLBACK=1``) —
    the acceptance signal is ``host_gap_ms_mesh`` at or below it: the
    compiled mesh step does strictly less host work than the loop it
    replaced."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, parallel, profiler, telemetry
    from incubator_mxnet_tpu.analysis import hlo

    if len(jax.devices()) < 8:
        raise RuntimeError(
            "--mesh-step needs 8 forced host devices "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    rng = onp.random.RandomState(0)
    x = rng.randn(16, 64).astype("float32")
    y = rng.randint(0, 8, (16,)).astype("float32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def probe(mesh, fallback=False):
        # pin the path EXPLICITLY both ways: a user-set
        # MXTPU_KVSTORE_FALLBACK=1 in the environment must not turn the
        # "mesh" half of the comparison into a second loop measurement
        prev = os.environ.get("MXTPU_KVSTORE_FALLBACK")
        os.environ["MXTPU_KVSTORE_FALLBACK"] = "1" if fallback else "0"
        try:
            mx.random.seed(7)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Dense(128, activation="relu", in_units=64),
                    gluon.nn.Dense(8, in_units=128))
            net.initialize(mx.init.Xavier())
            tr = parallel.ShardedTrainer(net, loss_fn, "adamw",
                                         {"learning_rate": 1e-3}, mesh=mesh)
            tr.step(x, y).asnumpy()        # init + compile
            batch = tr.place(x, y)         # steady state: resident inputs
            tr.step(*batch).asnumpy()      # warm
            profiler.reset_spans()
            for _ in range(steps):
                tr.step(*batch)
            tr.sync_to_block()             # one honest sync at the end
            sr = profiler.step_report(frame="step")
            return tr, sr
        finally:
            if prev is None:
                os.environ.pop("MXTPU_KVSTORE_FALLBACK", None)
            else:
                os.environ["MXTPU_KVSTORE_FALLBACK"] = prev

    tr_mesh, sr_mesh = probe(parallel.make_mesh(dp=4, tp=2))
    # the pre-pjit path: unsharded, per-parameter kvstore loop
    _, sr_one = probe(parallel.make_mesh(devices=jax.devices()[:1]),
                      fallback=True)
    rep = hlo.cost(tr_mesh, sample_args=(x, y))
    head = rep.head
    record = {
        "mesh": "dp=4,tp=2", "steps": steps,
        "flops_per_step": rep.model_flops_per_step(),
        "bytes_per_step": rep.bytes_per_step(),
        "comm_bytes_per_step": rep.comm_bytes_per_step(),
        # int total under the SAME key shape as the family records; the
        # verb split rides under its own name
        "collective_ops": rep.collective_ops_per_step(),
        "collective_ops_by_verb": dict(head.collective_ops) if head else {},
        "host_gap_ms_mesh": sr_mesh["host_gap_ms_mean"],
        "host_gap_ms_unsharded": sr_one["host_gap_ms_mean"],
        "path": tr_mesh.last_path,
    }
    telemetry.emit("perf.proxy", family="mesh_step", **record)
    return record


def run_proxy(argv) -> int:
    """CPU-only proxy bench: one record per serving family, optional
    banked write (``--out``) and tolerance gate (``--check``)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench.py --proxy",
        description="device-blind perf proxy over the serving zoo")
    ap.add_argument("--proxy", action="store_true")
    ap.add_argument("--families", default="all",
                    help="comma-separated models.SERVE_SPECS families, "
                         "or 'all' (default)")
    ap.add_argument("--mesh-step", action="store_true",
                    help="also probe the compiled mesh training step on 8 "
                         "forced host devices (host-gap vs unsharded + "
                         "collective comm record; reported, never banked)")
    ap.add_argument("--out", default=None,
                    help="write/refresh the banked baseline JSON here")
    ap.add_argument("--check", default=None,
                    help="banked baseline JSON to gate against")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="relative gate tolerance (default 0.05 = ±5%%)")
    ap.add_argument("--iters", type=int, default=4,
                    help="steady-state predict calls for the host-gap "
                         "probe")
    args = ap.parse_args(argv)

    # the proxy is device-blind by design: pin cpu so it never claims the
    # single-client TPU tunnel (same dance as tools/mxlint); the mesh-step
    # probe needs the 8-device virtual mesh. APPEND the device-count flag
    # when absent (same dance as tools/multichip_smoke) — setdefault would
    # let any pre-set XLA_FLAGS silently defeat it.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            + ("8" if args.mesh_step else "1")).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    from incubator_mxnet_tpu import models

    if args.families == "all":
        families = sorted(models.SERVE_SPECS)
    else:
        families = [f.strip() for f in args.families.split(",") if f.strip()]
        unknown = [f for f in families if f not in models.SERVE_SPECS]
        if unknown:
            print(f"bench.py --proxy: unknown families {unknown}; known: "
                  f"{sorted(models.SERVE_SPECS)}", file=sys.stderr)
            return 2

    try:
        fams = {f: _proxy_record(f, iters=args.iters) for f in families}
        # the calibrated int8 twins ride along for every selected family
        # that has one — banked under their own "int8" section so the
        # "families" set stays exactly models.SERVE_SPECS
        int8 = {f + "_int8": _proxy_record_int8(f, iters=args.iters)
                for f in families if f in models.QUANT_FAMILIES}
    except RuntimeError as e:
        print(f"bench.py {e}", file=sys.stderr)
        return 2
    # the train-side record: whole-step capture metrics (fused vs
    # unfused graph counts + the fused step graph's deterministic cost),
    # banked under its own "train" section so the serve-family set stays
    # exactly models.SERVE_SPECS
    train = {"fused_step": _fused_step_record()}
    mesh_step = None
    if args.mesh_step:
        try:
            mesh_step = _mesh_step_record()
        except RuntimeError as e:
            # the probe needs 8 forced host devices; a device shortfall
            # must not void the family gate that needed nothing from it
            print(f"bench.py --mesh-step: {e}", file=sys.stderr)
            mesh_step = {"error": str(e)}

    gate = None
    failures, warns = [], []
    if args.check:
        try:
            with open(args.check) as f:
                baseline = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench.py --proxy: cannot read baseline {args.check}: "
                  f"{e}", file=sys.stderr)
            return 2
        banked_jax = baseline.get("jax")
        if banked_jax and banked_jax != jax.__version__:
            # the cost table is a function of the jaxpr this jax version
            # emits — a drifted gate result needs this context to diagnose
            print(f"bench.py --proxy: note: baseline was banked on jax "
                  f"{banked_jax}, running jax {jax.__version__} — lowering "
                  "differences can shift the deterministic metrics",
                  file=sys.stderr)
        failures, warns = _proxy_compare(
            fams, baseline.get("families", {}), args.tolerance)
        q_fail, q_warn = _proxy_compare(
            int8, baseline.get("int8", {}), args.tolerance)
        t_fail, t_warn = _proxy_compare(
            train, baseline.get("train", {}), args.tolerance)
        failures += q_fail + t_fail
        warns += q_warn + t_warn
        gate = {"baseline": args.check, "tolerance": args.tolerance,
                "failures": failures, "warnings": warns}
        # the whole-trajectory view rides along with the per-graph gate:
        # best banked config, blind-round count, and any measured-round
        # regression flag from the merged BENCH/BASELINE/PERF_PROXY
        # artifacts (tools/perf_history.py — flags surface as warnings
        # here; the goodput-smoke CI job gates on them via --check)
        try:
            from tools import perf_history as _ph
            hist_root = os.path.dirname(os.path.abspath(args.check)) or "."
            gate["perf_history"] = _ph.summary(hist_root, args.tolerance)
            for flag in gate["perf_history"]["regressions"]:
                warns.append(f"perf_history: {flag}")
        except Exception as e:  # noqa: BLE001 — the trajectory is
            gate["perf_history"] = {"error": str(e)}  # context, not a gate
        for w in warns:
            print(f"bench.py --proxy: WARN {w}", file=sys.stderr)
        for fl in failures:
            print(f"bench.py --proxy: FAIL {fl}", file=sys.stderr)

    if args.out:
        banked = {"format": 1, "tolerance": args.tolerance,
                  "generated_by": "python bench.py --proxy --out",
                  "jax": jax.__version__,
                  "families": {
                      f: {k: v for k, v in rec.items()
                          if k not in _PROXY_VOLATILE_KEYS}
                      for f, rec in sorted(fams.items())},
                  "int8": {
                      f: {k: v for k, v in rec.items()
                          if k not in _PROXY_VOLATILE_KEYS}
                      for f, rec in sorted(int8.items())},
                  "train": {
                      f: {k: v for k, v in rec.items()
                          if k not in _PROXY_VOLATILE_KEYS}
                      for f, rec in sorted(train.items())}}
        tmp = f"{args.out}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(banked, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.out)

    total_flops = sum(rec["flops_per_step"] for rec in fams.values())
    result = {
        "metric": "perf_proxy_flops_per_step",
        "value": total_flops,
        "unit": "flops/step (sum over families)",
        "vs_baseline": None,
        "extra": {"families": fams, "int8": int8, "train": train,
                  "gate": gate, "backend": jax.default_backend()},
    }
    if mesh_step is not None:
        result["extra"]["mesh_step"] = mesh_step
    print(json.dumps(result))
    return 1 if failures else 0


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if "--proxy" in argv:
        raise SystemExit(run_proxy(argv))
    watchdog = _arm_watchdog()
    workload = _bench_workload()
    if workload == "resnet":
        print(json.dumps(run_resnet(watchdog)))
        return
    if workload == "ssd":
        print(json.dumps(run_ssd(watchdog)))
        return
    if workload == "frcnn":
        print(json.dumps(run_frcnn(watchdog)))
        return
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import models, parallel

    model_name = _bench_model("bert")
    B = int(os.environ.get("MXTPU_BENCH_BATCH", "8"))
    L = int(os.environ.get("MXTPU_BENCH_SEQ", "512"))
    peak_tflops = _peak_tflops()
    steps = int(os.environ.get("MXTPU_BENCH_STEPS", "20"))
    vocab = 30522
    P = max(1, round(0.15 * L))  # BERT's 15% masking rate

    remat = os.environ.get("MXTPU_BENCH_REMAT", "0") == "1"
    dropout = float(os.environ.get("MXTPU_BENCH_DROPOUT", "0.1"))
    cfg = models.bert.BERT_CONFIGS[model_name]
    net = models.get_bert(model_name, vocab_size=vocab, max_length=L,
                          dropout=dropout, dtype="bfloat16", remat=remat)
    net.initialize()
    mesh = parallel.make_mesh(devices=jax.devices()[:1])
    trainer = parallel.ShardedTrainer(
        net, models.bert_pretrain_loss, "adamw",
        {"learning_rate": 1e-4, "multi_precision": True}, mesh=mesh,
        rules=models.bert_sharding_rules(), n_labels=3,
        # banked autotune winners (MXTPU_AUTOTUNE_DIR) apply at build —
        # a tuned config is reproducible per key, not a one-off env
        # recipe pasted into a shell
        autotune_key="bert")

    rng = onp.random.RandomState(0)
    ids = rng.randint(0, vocab, (B, L)).astype("int32")
    tt = rng.randint(0, 2, (B, L)).astype("int32")
    vl = onp.full((B,), L, "float32")
    pos = rng.randint(0, L, (B, P)).astype("int32")
    mlm_lab = rng.randint(0, vocab, (B, P)).astype("float32")
    mlm_w = onp.ones((B, P), "float32")
    nsp = rng.randint(0, 2, (B,)).astype("float32")
    batch = (ids, tt, vl, pos, mlm_lab, mlm_w, nsp)

    dt, loss = _measure(trainer, batch, steps, watchdog)

    tokens_per_sec = B * L / dt
    # Transformer pretraining FLOPs: 6 * n_params * n_tokens for the
    # matmul-dominated path + attention term 12 * layers * units * L² * B
    # (fwd+bwd), the standard PaLM-appendix accounting.
    n_params = sum(int(onp.prod(p.shape))
                   for _, p in net.collect_params().items())
    flops = 6 * n_params * B * L + 12 * cfg["num_layers"] * cfg["units"] * L * L * B
    mfu = (flops / dt) / (peak_tflops * 1e12)
    result = {
        "metric": f"{model_name}_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"step_ms": round(dt * 1e3, 2), "mfu": round(mfu, 4),
                  "batch": B, "seq": L, "remat": remat, "params": n_params,
                  "backend": jax.default_backend(),
                  "loss": float(loss.asnumpy())},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Self-contained ONNX protobuf wire-format codec.

The build environment has no ``onnx`` package (and no network), but ONNX
files are ordinary protobuf — so this module implements the subset of the
public ``onnx.proto`` schema the converter needs, directly on the protobuf
wire format (varint + length-delimited fields). Files written here load in
stock ``onnx``/onnxruntime, and vice versa for models made of the supported
message subset. Field numbers follow the published onnx.proto (stable since
IR version 3):

- ModelProto:    ir_version=1, producer_name=2, graph=7, opset_import=8
- GraphProto:    node=1, name=2, initializer=5, input=11, output=12
- NodeProto:     input=1, output=2, name=3, op_type=4, attribute=5
- AttributeProto: name=1, f=2, i=3, s=4, floats=7, ints=8, type=20
- TensorProto:   dims=1, data_type=2, name=8, raw_data=9
- ValueInfoProto: name=1, type=2 / TypeProto.tensor_type=1 /
  Tensor.elem_type=1, shape=2 / TensorShapeProto.dim=1 / Dimension.dim_value=1
- OperatorSetIdProto: domain=1, version=2
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as onp

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(fieldno: int, wire: int) -> bytes:
    return _varint((fieldno << 3) | wire)


def _len_delim(fieldno: int, payload: bytes) -> bytes:
    return _tag(fieldno, 2) + _varint(len(payload)) + payload


def _vint_field(fieldno: int, value: int) -> bytes:
    return _tag(fieldno, 0) + _varint(value)


def _f32_field(fieldno: int, value: float) -> bytes:
    return _tag(fieldno, 5) + struct.pack("<f", value)


def _signed(v: int) -> int:
    """Fold a decoded uint64 varint back to two's-complement int64 (protobuf
    int64 wire form — negative attribute values like axis=-1)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _fields(buf: bytes):
    """Yield (fieldno, wire, value, ) over a serialized message."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        fieldno, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wire}")
        yield fieldno, wire, v


# ---------------------------------------------------------------------------
# message classes (attribute-compatible with the real onnx package for the
# fields the converter touches)
# ---------------------------------------------------------------------------

#: onnx.TensorProto.DataType values
FLOAT, UINT8, INT8, INT32, INT64, BOOL = 1, 2, 3, 6, 7, 9
_NP2ONNX = {onp.dtype("float32"): FLOAT, onp.dtype("uint8"): UINT8,
            onp.dtype("int8"): INT8, onp.dtype("int32"): INT32,
            onp.dtype("int64"): INT64, onp.dtype("bool"): BOOL}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING = 1, 2, 3
ATTR_FLOATS, ATTR_INTS = 6, 7


class TensorProto:
    FLOAT, UINT8, INT8, INT32, INT64, BOOL = FLOAT, UINT8, INT8, INT32, \
        INT64, BOOL

    def __init__(self, name="", dims=(), data_type=FLOAT, raw_data=b""):
        self.name = name
        self.dims = list(dims)
        self.data_type = data_type
        self.raw_data = raw_data

    def encode(self) -> bytes:
        out = b"".join(_vint_field(1, d) for d in self.dims)
        out += _vint_field(2, self.data_type)
        out += _len_delim(8, self.name.encode())
        out += _len_delim(9, self.raw_data)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "TensorProto":
        t = cls()
        for fno, _, v in _fields(buf):
            if fno == 1:
                t.dims.append(v)
            elif fno == 2:
                t.data_type = v
            elif fno == 8:
                t.name = v.decode()
            elif fno == 9:
                t.raw_data = bytes(v)
            elif fno == 4:   # float_data fallback (packed)
                t.raw_data += bytes(v) if isinstance(v, (bytes, bytearray)) \
                    else struct.pack("<f", v)
        return t


@dataclass
class Dimension:
    dim_value: int = 0


@dataclass
class TensorShape:
    dim: List[Dimension] = field(default_factory=list)


@dataclass
class TensorTypeProto:
    elem_type: int = FLOAT
    shape: TensorShape = field(default_factory=TensorShape)


@dataclass
class TypeProto:
    tensor_type: TensorTypeProto = field(default_factory=TensorTypeProto)


class ValueInfoProto:
    def __init__(self, name="", elem_type=FLOAT, shape=()):
        self.name = name
        self.type = TypeProto(TensorTypeProto(
            elem_type, TensorShape([Dimension(int(d)) for d in shape])))

    def encode(self) -> bytes:
        tt = self.type.tensor_type
        shape_pb = b"".join(
            _len_delim(1, _vint_field(1, d.dim_value))
            for d in tt.shape.dim)
        tensor_pb = _vint_field(1, tt.elem_type) + _len_delim(2, shape_pb)
        type_pb = _len_delim(1, tensor_pb)
        return _len_delim(1, self.name.encode()) + _len_delim(2, type_pb)

    @classmethod
    def decode(cls, buf: bytes) -> "ValueInfoProto":
        vi = cls()
        for fno, _, v in _fields(buf):
            if fno == 1:
                vi.name = v.decode()
            elif fno == 2:
                for f2, _, v2 in _fields(v):
                    if f2 == 1:  # tensor_type
                        for f3, _, v3 in _fields(v2):
                            if f3 == 1:
                                vi.type.tensor_type.elem_type = v3
                            elif f3 == 2:
                                dims = []
                                for f4, _, v4 in _fields(v3):
                                    if f4 == 1:
                                        dv = 0
                                        for f5, _, v5 in _fields(v4):
                                            if f5 == 1:
                                                dv = v5
                                        dims.append(Dimension(dv))
                                vi.type.tensor_type.shape.dim = dims
        return vi


class AttributeProto:
    def __init__(self, name="", type=ATTR_INT, i=0, f=0.0, s=b"",
                 ints=(), floats=()):
        self.name = name
        self.type = type
        self.i = i
        self.f = f
        self.s = s
        self.ints = list(ints)
        self.floats = list(floats)

    def encode(self) -> bytes:
        out = _len_delim(1, self.name.encode())
        if self.type == ATTR_FLOAT:
            out += _f32_field(2, self.f)
        elif self.type == ATTR_INT:
            out += _vint_field(3, self.i)
        elif self.type == ATTR_STRING:
            out += _len_delim(4, self.s)
        elif self.type == ATTR_FLOATS:
            for v in self.floats:
                out += _f32_field(7, v)
        elif self.type == ATTR_INTS:
            for v in self.ints:
                out += _vint_field(8, v)
        out += _vint_field(20, self.type)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "AttributeProto":
        a = cls()
        for fno, wire, v in _fields(buf):
            if fno == 1:
                a.name = v.decode()
            elif fno == 2:
                a.f = v
            elif fno == 3:
                a.i = _signed(v)
            elif fno == 4:
                a.s = bytes(v)
            elif fno == 7:
                a.floats.append(v)
            elif fno == 8:
                if wire == 2:  # packed
                    i = 0
                    while i < len(v):
                        n, i = _read_varint(v, i)
                        a.ints.append(_signed(n))
                else:
                    a.ints.append(_signed(v))
            elif fno == 20:
                a.type = v
        return a


class NodeProto:
    def __init__(self, op_type="", inputs=(), outputs=(), name="",
                 attribute=()):
        self.op_type = op_type
        self.input = list(inputs)
        self.output = list(outputs)
        self.name = name
        self.attribute = list(attribute)

    def encode(self) -> bytes:
        out = b"".join(_len_delim(1, s.encode()) for s in self.input)
        out += b"".join(_len_delim(2, s.encode()) for s in self.output)
        out += _len_delim(3, self.name.encode())
        out += _len_delim(4, self.op_type.encode())
        out += b"".join(_len_delim(5, a.encode()) for a in self.attribute)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "NodeProto":
        n = cls()
        for fno, _, v in _fields(buf):
            if fno == 1:
                n.input.append(v.decode())
            elif fno == 2:
                n.output.append(v.decode())
            elif fno == 3:
                n.name = v.decode()
            elif fno == 4:
                n.op_type = v.decode()
            elif fno == 5:
                n.attribute.append(AttributeProto.decode(v))
        return n


class GraphProto:
    def __init__(self, nodes=(), name="", initializer=(), inputs=(),
                 outputs=()):
        self.node = list(nodes)
        self.name = name
        self.initializer = list(initializer)
        self.input = list(inputs)
        self.output = list(outputs)

    def encode(self) -> bytes:
        out = b"".join(_len_delim(1, n.encode()) for n in self.node)
        out += _len_delim(2, self.name.encode())
        out += b"".join(_len_delim(5, t.encode()) for t in self.initializer)
        out += b"".join(_len_delim(11, vi.encode()) for vi in self.input)
        out += b"".join(_len_delim(12, vi.encode()) for vi in self.output)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "GraphProto":
        g = cls()
        for fno, _, v in _fields(buf):
            if fno == 1:
                g.node.append(NodeProto.decode(v))
            elif fno == 2:
                g.name = v.decode()
            elif fno == 5:
                g.initializer.append(TensorProto.decode(v))
            elif fno == 11:
                g.input.append(ValueInfoProto.decode(v))
            elif fno == 12:
                g.output.append(ValueInfoProto.decode(v))
        return g


class ModelProto:
    def __init__(self, graph: Optional[GraphProto] = None,
                 ir_version: int = 8, opset: int = 13,
                 producer_name: str = "incubator_mxnet_tpu"):
        self.graph = graph if graph is not None else GraphProto()
        self.ir_version = ir_version
        self.opset = opset
        self.producer_name = producer_name

    def encode(self) -> bytes:
        opset_pb = _len_delim(1, b"") + _vint_field(2, self.opset)
        return (_vint_field(1, self.ir_version)
                + _len_delim(2, self.producer_name.encode())
                + _len_delim(7, self.graph.encode())
                + _len_delim(8, opset_pb))

    @classmethod
    def decode(cls, buf: bytes) -> "ModelProto":
        m = cls()
        for fno, _, v in _fields(buf):
            if fno == 1:
                m.ir_version = v
            elif fno == 2:
                m.producer_name = v.decode()
            elif fno == 7:
                m.graph = GraphProto.decode(v)
            elif fno == 8:
                for f2, _, v2 in _fields(v):
                    if f2 == 2:
                        m.opset = v2
        return m


# ---------------------------------------------------------------------------
# onnx.helper / numpy_helper compatible surface used by the converter
# ---------------------------------------------------------------------------

class helper:  # noqa: N801 — mirrors the onnx.helper module name
    @staticmethod
    def make_tensor_value_info(name, elem_type, shape):
        return ValueInfoProto(name, elem_type, shape or ())

    @staticmethod
    def make_node(op_type, inputs, outputs, name="", **attrs):
        alist = []
        for k, v in attrs.items():
            if isinstance(v, bool):
                alist.append(AttributeProto(k, ATTR_INT, i=int(v)))
            elif isinstance(v, int):
                alist.append(AttributeProto(k, ATTR_INT, i=v))
            elif isinstance(v, float):
                alist.append(AttributeProto(k, ATTR_FLOAT, f=v))
            elif isinstance(v, str):
                alist.append(AttributeProto(k, ATTR_STRING, s=v.encode()))
            elif isinstance(v, (list, tuple)) and v and \
                    isinstance(v[0], float):
                alist.append(AttributeProto(k, ATTR_FLOATS, floats=v))
            else:
                alist.append(AttributeProto(
                    k, ATTR_INTS, ints=[int(x) for x in v]))
        return NodeProto(op_type, inputs, outputs, name, alist)

    @staticmethod
    def make_graph(nodes, name, inputs, outputs, initializer=()):
        return GraphProto(nodes, name, initializer, inputs, outputs)

    @staticmethod
    def make_model(graph, **kw):
        return ModelProto(graph)

    @staticmethod
    def get_attribute_value(a: AttributeProto):
        if a.type == ATTR_FLOAT:
            return a.f
        if a.type == ATTR_INT:
            return a.i
        if a.type == ATTR_STRING:
            return a.s
        if a.type == ATTR_FLOATS:
            return list(a.floats)
        if a.type == ATTR_INTS:
            return list(a.ints)
        raise ValueError(f"unsupported attribute type {a.type}")


class numpy_helper:  # noqa: N801
    @staticmethod
    def from_array(arr: onp.ndarray, name: str = "") -> TensorProto:
        arr = onp.ascontiguousarray(arr)
        if arr.dtype not in _NP2ONNX:
            raise ValueError(f"unsupported dtype {arr.dtype}")
        return TensorProto(name, arr.shape, _NP2ONNX[arr.dtype],
                           arr.tobytes())

    @staticmethod
    def to_array(t: TensorProto) -> onp.ndarray:
        dt = _ONNX2NP.get(t.data_type)
        if dt is None:
            raise ValueError(f"unsupported ONNX data_type {t.data_type}")
        return onp.frombuffer(t.raw_data, dtype=dt).reshape(t.dims)


def save(model: ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(model.encode())


def load(path: str) -> ModelProto:
    with open(path, "rb") as f:
        return ModelProto.decode(f.read())

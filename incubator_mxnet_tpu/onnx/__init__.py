"""ONNX interop (reference: python/mxnet/onnx — SURVEY §2.7).

``export_model`` emits a real ONNX ModelProto for symbol graphs of the
supported op set; ``import_model`` reads one back into
``(sym, arg_params, aux_params)``. The environment has no ``onnx`` package,
so serialization runs on the in-tree wire-format codec (``_proto.py`` —
plain protobuf; files interchange with stock onnx/onnxruntime). When a real
``onnx`` package IS present it is used instead.

Supported op set (the gluon model-zoo surface): FullyConnected/Gemm,
Convolution/Conv (pads/strides/dilations/groups), Pooling (max/avg,
pads/ceil_mode/global), BatchNorm, Dropout, Flatten, Reshape, Transpose,
Concat, elementwise broadcast_{add,sub,mul,div}, activations
(relu/sigmoid/tanh/softrelu), softmax/SoftmaxOutput, and multi-output
graphs via ``sym.Group``.

The deploy-format story on TPU is StableHLO (``HybridBlock.export`` /
``jax.export``) — ONNX remains for ecosystem exchange.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError

__all__ = ["export_model", "import_model", "get_model_metadata"]


def _onnx_mods():
    """(helper, numpy_helper, TensorProto, save, load) from the real onnx
    package when importable, else the in-tree codec."""
    try:
        import onnx  # noqa: F401
        from onnx import TensorProto, helper, numpy_helper
        return helper, numpy_helper, TensorProto, onnx.save, onnx.load
    except ImportError:
        from . import _proto
        return (_proto.helper, _proto.numpy_helper, _proto.TensorProto,
                _proto.save, _proto.load)


_SIMPLE_MAP = {
    "flatten": "Flatten",
    "Flatten": "Flatten",
    "softmax": "Softmax",
    "SoftmaxOutput": "Softmax",
    "broadcast_add": "Add",
    "broadcast_sub": "Sub",
    "broadcast_mul": "Mul",
    "broadcast_div": "Div",
    "elemwise_add": "Add",
    "relu": "Relu",
    "sigmoid": "Sigmoid",
    "tanh": "Tanh",
}

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus"}


def _export_node(node, helper, out_names):
    """One symbol node -> ONNX NodeProto(s)."""
    op = node._op
    attrs_in = node._attrs
    ins = [out_names[id(i)] for i in node._inputs if id(i) in out_names]
    name = node._name
    attrs = {}
    if op == "Activation":
        onnx_op = _ACT_MAP.get(attrs_in.get("act_type", "relu"), "Relu")
    elif op in _SIMPLE_MAP:
        onnx_op = _SIMPLE_MAP[op]
        if op == "SoftmaxOutput":
            ins = ins[:1]
        if op in ("softmax", "SoftmaxOutput"):
            attrs["axis"] = int(attrs_in.get("axis", -1))
    elif op == "FullyConnected":
        onnx_op = "Gemm"
        attrs.update(alpha=1.0, beta=1.0, transA=0, transB=1)
    elif op == "Convolution":
        onnx_op = "Conv"
        k = list(attrs_in.get("kernel", (1, 1)))
        attrs["kernel_shape"] = k
        attrs["strides"] = list(attrs_in.get("stride") or (1,) * len(k))
        pad = list(attrs_in.get("pad") or (0,) * len(k))
        attrs["pads"] = pad + pad        # onnx: begin then end per axis
        attrs["dilations"] = list(attrs_in.get("dilate") or (1,) * len(k))
        attrs["group"] = int(attrs_in.get("num_group", 1))
    elif op == "Pooling":
        ptype = attrs_in.get("pool_type", "max")
        if attrs_in.get("global_pool"):
            onnx_op = "GlobalAveragePool" if ptype == "avg" \
                else "GlobalMaxPool"
        else:
            onnx_op = "AveragePool" if ptype == "avg" else "MaxPool"
            k = list(attrs_in.get("kernel", (2, 2)))
            attrs["kernel_shape"] = k
            # in-tree Pooling defaults stride to 1 per dim (ops/nn.py), the
            # same as the ONNX spec default — only record explicit strides
            attrs["strides"] = list(attrs_in.get("stride") or (1,) * len(k))
            pad = list(attrs_in.get("pad") or (0,) * len(k))
            attrs["pads"] = pad + pad
            if attrs_in.get("pooling_convention") == "full":
                attrs["ceil_mode"] = 1
    elif op == "BatchNorm":
        onnx_op = "BatchNormalization"
        attrs["epsilon"] = float(attrs_in.get("eps", 1e-5))
        attrs["momentum"] = float(attrs_in.get("momentum", 0.9))
        # symbol input order is (data, gamma, beta, moving_mean, moving_var)
        # = onnx (X, scale, B, mean, var)
    elif op == "Dropout":
        onnx_op = "Dropout"
        # inference graph: identity semantics; ratio recorded for fidelity
        attrs["ratio"] = float(attrs_in.get("p", 0.5))
    elif op in ("reshape", "Reshape"):
        onnx_op = "Reshape"
        # shape travels as an initializer input in opset>=5; appended later
    elif op in ("transpose",):
        onnx_op = "Transpose"
        axes = attrs_in.get("axes")
        if axes:
            attrs["perm"] = list(axes)
    elif op in ("concat", "Concat"):
        onnx_op = "Concat"
        attrs["axis"] = int(attrs_in.get("dim", attrs_in.get("axis", 1)))
    else:
        raise MXNetError(f"op {op!r} has no ONNX mapping yet")
    return helper.make_node(onnx_op, ins, [name], name=name, **attrs), attrs_in


def export_model(sym, params: Dict, input_shape: Sequence[Tuple[int, ...]],
                 input_type=onp.float32, onnx_file_path: str = "model.onnx",
                 verbose: bool = False, opset_version: Optional[int] = None):
    """Export a symbol + params dict to an ONNX file
    (reference: mx.onnx.export_model). Multi-output graphs via sym.Group."""
    helper, numpy_helper, TensorProto, onnx_save, _ = _onnx_mods()
    from ..symbol import Symbol, _topo

    if not isinstance(sym, Symbol):
        raise MXNetError("export_model expects a Symbol (use "
                         "HybridBlock.export for Gluon models)")
    nodes = _topo(sym)
    arg_names = sym.list_arguments()
    data_names = [n for n in arg_names if n not in params]
    if len(data_names) != len(input_shape):
        data_names = data_names[:len(input_shape)]

    inits, inputs, onnx_nodes = [], [], []
    for name, shape in zip(data_names, input_shape):
        inputs.append(helper.make_tensor_value_info(
            name, TensorProto.FLOAT, list(shape)))
    for name, arr in params.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else onp.asarray(arr)
        inits.append(numpy_helper.from_array(a.astype(onp.float32), name))

    out_names: Dict[int, str] = {}
    group_outputs: List = []
    for node in nodes:
        if node._op is None and node._base is None:
            out_names[id(node)] = node._name
            continue
        if node._op == "_group":
            group_outputs = list(node._inputs)
            continue
        if node._base is not None:       # multi-output slice: same tensor
            out_names[id(node)] = out_names[id(node._base)]
            continue
        pb_node, attrs_in = _export_node(node, helper, out_names)
        if pb_node.op_type == "Reshape":
            shape_name = node._name + "_shape"
            inits.append(numpy_helper.from_array(
                onp.asarray(attrs_in.get("shape", ()), onp.int64),
                shape_name))
            pb_node.input.append(shape_name)
        out_names[id(node)] = node._name
        onnx_nodes.append(pb_node)

    known = {n: s for n, s in zip(data_names, input_shape)}
    out_shapes = sym.infer_shape(**known)[1]
    outs = group_outputs if group_outputs else [nodes[-1]]
    outputs = [helper.make_tensor_value_info(
        out_names[id(o)], TensorProto.FLOAT,
        list(s) if s is not None else None)
        for o, s in zip(outs, out_shapes)]
    graph = helper.make_graph(onnx_nodes, "incubator_mxnet_tpu", inputs,
                              outputs, initializer=inits)
    model = helper.make_model(graph)
    onnx_save(model, onnx_file_path)
    return onnx_file_path


#: onnx op -> symbol op for import
_REV = {"Gemm": "FullyConnected", "Conv": "Convolution", "Relu": "relu",
        "Sigmoid": "sigmoid", "Tanh": "tanh", "Softplus": "softrelu",
        "Softmax": "softmax", "Add": "broadcast_add",
        "Sub": "broadcast_sub", "Mul": "broadcast_mul",
        "Div": "broadcast_div", "Flatten": "flatten",
        "MaxPool": "Pooling", "AveragePool": "Pooling",
        "GlobalMaxPool": "Pooling", "GlobalAveragePool": "Pooling",
        "BatchNormalization": "BatchNorm", "Dropout": "Dropout",
        "Reshape": "reshape", "Transpose": "transpose", "Concat": "concat"}


def import_model(model_file: str):
    """Import an ONNX model into (sym, arg_params, aux_params)
    (reference: mx.onnx.import_model). Supports the export op subset;
    multi-output graphs come back as a sym.Group."""
    helper, numpy_helper, TensorProto, _, onnx_load = _onnx_mods()
    from .. import symbol as S
    from ..ndarray import array

    model = onnx_load(model_file)
    g = model.graph
    raw_params = {init.name: numpy_helper.to_array(init)
                  for init in g.initializer}
    env: Dict[str, S.Symbol] = {}
    for vi in g.input:
        if vi.name not in raw_params:
            env[vi.name] = S.Variable(vi.name)
    for name in raw_params:
        env[name] = S.Variable(name)

    shape_consts = {}                      # Reshape shape initializers
    aux_names = set()
    for node in g.node:
        if node.op_type not in _REV:
            raise MXNetError(f"ONNX op {node.op_type!r} unsupported on import")
        op = _REV[node.op_type]
        attrs = {a.name: helper.get_attribute_value(a)
                 for a in node.attribute}
        kw = {}
        ins_names = list(node.input)
        if op == "reshape":
            shape = raw_params.get(ins_names[1])
            if shape is None:
                raise MXNetError("Reshape without constant shape input")
            shape_consts[ins_names[1]] = True
            kw["shape"] = tuple(int(d) for d in shape)
            ins_names = ins_names[:1]
        if op == "FullyConnected":
            w = raw_params.get(node.input[1])
            kw["num_hidden"] = int(w.shape[0]) if w is not None else 0
            if int(attrs.get("transB", 0)) != 1:
                raise MXNetError("Gemm import requires transB=1 "
                                 "(weight as (out, in))")
        if op == "Convolution":
            kw["kernel"] = tuple(attrs.get("kernel_shape", (1, 1)))
            kw["stride"] = tuple(attrs.get("strides", (1, 1)))
            pads = attrs.get("pads", [0, 0, 0, 0])
            kw["pad"] = tuple(pads[:len(pads) // 2])
            kw["dilate"] = tuple(attrs.get("dilations",
                                           (1,) * len(kw["kernel"])))
            kw["num_group"] = int(attrs.get("group", 1))
            w = raw_params.get(node.input[1])
            kw["num_filter"] = int(w.shape[0]) if w is not None else 0
        if op == "Pooling":
            if node.op_type.startswith("Global"):
                kw["global_pool"] = True
                kw["pool_type"] = ("avg" if "Average" in node.op_type
                                   else "max")
                kw["kernel"] = (1, 1)
            else:
                kw["pool_type"] = ("avg" if node.op_type == "AveragePool"
                                   else "max")
                kw["kernel"] = tuple(attrs.get("kernel_shape", (2, 2)))
                # ONNX spec: strides default to 1 along each axis
                kw["stride"] = tuple(
                    attrs.get("strides", (1,) * len(kw["kernel"])))
                pads = attrs.get("pads", [0, 0, 0, 0])
                kw["pad"] = tuple(pads[:len(pads) // 2])
                if int(attrs.get("ceil_mode", 0)):
                    kw["pooling_convention"] = "full"
        if op == "BatchNorm":
            kw["eps"] = float(attrs.get("epsilon", 1e-5))
            kw["momentum"] = float(attrs.get("momentum", 0.9))
            aux_names.update(node.input[3:5])
        if op == "softmax":
            kw["axis"] = int(attrs.get("axis", -1))
        if op == "transpose" and "perm" in attrs:
            kw["axes"] = tuple(attrs["perm"])
        if op == "concat":
            kw["dim"] = int(attrs.get("axis", 1))
        if op == "Dropout":
            kw["p"] = float(attrs.get("ratio", 0.5))
        ins = [env[i] for i in ins_names if i in env]
        out_sym = S.Symbol(op, ins, attrs=kw, name=node.name or None)
        for out_name in node.output:
            env[out_name] = out_sym
    outs = [env[o.name] for o in g.output if o.name in env]
    sym = outs[0] if len(outs) == 1 else S.Group(outs)
    arg_params = {k: array(v) for k, v in raw_params.items()
                  if k not in shape_consts and k not in aux_names}
    aux_params = {k: array(raw_params[k]) for k in aux_names
                  if k in raw_params}
    return sym, arg_params, aux_params


def get_model_metadata(model_file: str) -> Dict:
    helper, numpy_helper, TensorProto, _, onnx_load = _onnx_mods()
    model = onnx_load(model_file)
    g = model.graph
    init_names = {i.name for i in g.initializer}
    return {
        "input_tensor_data": [(vi.name,
                               tuple(d.dim_value
                                     for d in vi.type.tensor_type.shape.dim))
                              for vi in g.input if vi.name not in init_names],
        "output_tensor_data": [(vi.name,
                                tuple(d.dim_value
                                      for d in vi.type.tensor_type.shape.dim))
                               for vi in g.output],
    }

"""ONNX interop (reference: python/mxnet/onnx — SURVEY §2.7).

The ``onnx`` package is not part of this build's frozen environment, so the
conversion surface is API-complete but gated: with ``onnx`` installed,
``export_model`` emits a real ModelProto for symbol graphs made of the
supported op set; without it, a clear MXNetError explains the gate.

The deploy-format story on TPU is StableHLO (``HybridBlock.export`` /
``jax.export``) — ONNX remains for ecosystem exchange only.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError

__all__ = ["export_model", "import_model", "get_model_metadata"]


def _require_onnx():
    try:
        import onnx  # noqa: F401
        return onnx
    except ImportError:
        raise MXNetError(
            "ONNX interop requires the 'onnx' package, which is not "
            "installed in this environment. Use HybridBlock.export() "
            "(StableHLO + params) for the TPU-native deploy format.")


#: symbol-op -> (onnx op type, attr mapper)
_OP_MAP = {
    "FullyConnected": "Gemm",
    "Convolution": "Conv",
    "Activation": "Relu",  # refined by act_type
    "flatten": "Flatten",
    "Flatten": "Flatten",
    "Pooling": "MaxPool",
    "softmax": "Softmax",
    "SoftmaxOutput": "Softmax",
    "broadcast_add": "Add",
    "broadcast_sub": "Sub",
    "broadcast_mul": "Mul",
    "broadcast_div": "Div",
    "concat": "Concat",
    "relu": "Relu",
    "sigmoid": "Sigmoid",
    "tanh": "Tanh",
}

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus"}


def export_model(sym, params: Dict, input_shape: Sequence[Tuple[int, ...]],
                 input_type=onp.float32, onnx_file_path: str = "model.onnx",
                 verbose: bool = False, opset_version: Optional[int] = None):
    """Export a symbol + params dict to an ONNX file
    (reference: mx.onnx.export_model)."""
    onnx = _require_onnx()
    from onnx import TensorProto, helper, numpy_helper

    from ..symbol import Symbol, _topo

    if not isinstance(sym, Symbol):
        raise MXNetError("export_model expects a Symbol (use "
                         "HybridBlock.export for Gluon models)")
    nodes = _topo(sym)
    arg_names = sym.list_arguments()
    data_names = [n for n in arg_names if n not in params]
    if len(data_names) != len(input_shape):
        data_names = data_names[:len(input_shape)]

    inits, inputs, onnx_nodes = [], [], []
    for name, shape in zip(data_names, input_shape):
        inputs.append(helper.make_tensor_value_info(
            name, TensorProto.FLOAT, list(shape)))
    for name, arr in params.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else onp.asarray(arr)
        inits.append(numpy_helper.from_array(a.astype(onp.float32), name))

    out_names = {}
    for node in nodes:
        if node._op is None and node._base is None:
            out_names[id(node)] = node._name
            continue
        op = node._op
        if op not in _OP_MAP:
            raise MXNetError(f"op {op!r} has no ONNX mapping yet")
        onnx_op = _OP_MAP[op]
        attrs = {}
        if op == "Activation":
            onnx_op = _ACT_MAP.get(node._attrs.get("act_type", "relu"), "Relu")
        if op == "Pooling" and node._attrs.get("pool_type") == "avg":
            onnx_op = "AveragePool"
        if onnx_op in ("MaxPool", "AveragePool"):
            attrs["kernel_shape"] = list(node._attrs.get("kernel", (2, 2)))
            attrs["strides"] = list(node._attrs.get("stride", (1, 1)))
        if onnx_op == "Conv":
            attrs["kernel_shape"] = list(node._attrs.get("kernel", (1, 1)))
            attrs["strides"] = list(node._attrs.get("stride", (1, 1)) or (1, 1))
            attrs["pads"] = list(node._attrs.get("pad", (0, 0)) or (0, 0)) * 2
        if onnx_op == "Gemm":
            attrs.update(alpha=1.0, beta=1.0, transA=0, transB=1)
        ins = [out_names[id(i)] for i in node._inputs
               if id(i) in out_names]
        if op == "SoftmaxOutput":
            ins = ins[:1]
        name = node._name
        out_names[id(node)] = name
        onnx_nodes.append(helper.make_node(onnx_op, ins, [name], name=name,
                                           **attrs))

    out_shapes = sym.infer_shape(**{n: s for n, s in
                                    zip(data_names, input_shape)})[1]
    outputs = [helper.make_tensor_value_info(
        out_names[id(nodes[-1])], TensorProto.FLOAT, list(out_shapes[0]))]
    graph = helper.make_graph(onnx_nodes, "incubator_mxnet_tpu", inputs,
                              outputs, initializer=inits)
    model = helper.make_model(graph)
    onnx.save(model, onnx_file_path)
    return onnx_file_path


def import_model(model_file: str):
    """Import an ONNX model into (sym, arg_params, aux_params)
    (reference: mx.onnx.import_model). Supports the same op subset as
    export."""
    onnx = _require_onnx()
    from onnx import numpy_helper
    from .. import symbol as S
    from ..ndarray import array

    model = onnx.load(model_file)
    g = model.graph
    params = {init.name: array(numpy_helper.to_array(init))
              for init in g.initializer}
    env: Dict[str, S.Symbol] = {}
    for vi in g.input:
        if vi.name not in params:
            env[vi.name] = S.Variable(vi.name)
    for name in params:
        env[name] = S.Variable(name)
    _REV = {"Gemm": "FullyConnected", "Conv": "Convolution", "Relu": "relu",
            "Sigmoid": "sigmoid", "Tanh": "tanh", "Softmax": "softmax",
            "Add": "broadcast_add", "Sub": "broadcast_sub",
            "Mul": "broadcast_mul", "Div": "broadcast_div",
            "Flatten": "flatten", "MaxPool": "Pooling",
            "AveragePool": "Pooling"}
    for node in g.node:
        if node.op_type not in _REV:
            raise MXNetError(f"ONNX op {node.op_type!r} unsupported on import")
        op = _REV[node.op_type]
        ins = [env[i] for i in node.input if i in env]
        attrs = {a.name: onnx.helper.get_attribute_value(a)
                 for a in node.attribute}
        kw = {}
        if op == "FullyConnected":
            w = params.get(node.input[1])
            kw["num_hidden"] = int(w.shape[0]) if w is not None else 0
        if op == "Convolution":
            kw["kernel"] = tuple(attrs.get("kernel_shape", (1, 1)))
            kw["stride"] = tuple(attrs.get("strides", (1, 1)))
            pads = attrs.get("pads", [0, 0, 0, 0])
            kw["pad"] = tuple(pads[:2])
            w = params.get(node.input[1])
            kw["num_filter"] = int(w.shape[0]) if w is not None else 0
        if op == "Pooling":
            kw["pool_type"] = "avg" if node.op_type == "AveragePool" else "max"
            kw["kernel"] = tuple(attrs.get("kernel_shape", (2, 2)))
            kw["stride"] = tuple(attrs.get("strides", (1, 1)))
        env[node.output[0]] = S.Symbol(op, ins, attrs=kw, name=node.name or None)
    out = env[g.output[0].name] if g.output[0].name in env else \
        env[g.node[-1].output[0]]
    return out, params, {}


def get_model_metadata(model_file: str) -> Dict:
    onnx = _require_onnx()
    model = onnx.load(model_file)
    g = model.graph
    init_names = {i.name for i in g.initializer}
    return {
        "input_tensor_data": [(vi.name,
                               tuple(d.dim_value
                                     for d in vi.type.tensor_type.shape.dim))
                              for vi in g.input if vi.name not in init_names],
        "output_tensor_data": [(vi.name,
                                tuple(d.dim_value
                                      for d in vi.type.tensor_type.shape.dim))
                               for vi in g.output],
    }

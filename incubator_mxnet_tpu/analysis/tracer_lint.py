"""JAX-pitfall source linter (pass 3, static half).

The reference's ``hybrid_forward`` contract ("F is mx.nd or mx.sym — write
code that works under both") maps here to "the body is traced by jax.jit":
Python side effects on traced values are silent correctness/perf bugs the
reference never had. This linter walks ``forward``/``hybrid_forward`` bodies
of ``HybridBlock``-derived classes (``gluon/block.py`` lineage) with a small
taint analysis — the data arguments (and ``**param`` kwargs) are traced;
taint propagates through arithmetic, indexing, method calls and assignment,
and is *dropped* by static accessors (``.shape``/``.ndim``/``.dtype``,
``len``, ``isinstance``, ``str``) so shape-polymorphic idioms stay clean.

Flagged constructs (the MX2xx tracer-hygiene family):

- **MX202** ``print(traced)`` — executes once at trace time, then never
  again; the printed value is a tracer, not data.
- **MX203** ``float()/bool()/int()`` (or ``.item()``/``.asscalar()``) on a
  traced value — concretization error under jit, silent recompile trigger
  at best.
- **MX204** ``if``/``while``/``assert``/ternary on a traced value — Python
  control flow cannot branch on tracers; use ``F.where``/``lax.cond``.
- **MX205** host ``numpy`` calls (or ``.asnumpy()``/``.tolist()``) on a
  traced value — leaves the compiled graph, breaks under jit.
- **MX206** storing a traced value on ``self`` — the classic leaked-tracer
  bug: the attribute outlives the trace and poisons the next call
  (``UnexpectedTracerError``).

Pure-AST: no imports of the linted module, so models and examples lint in
milliseconds and broken files report a diagnostic instead of crashing.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, Report, walk_lint

__all__ = ["lint_source", "lint_file", "lint_paths"]

#: calls whose result is host data regardless of argument taint
_SANITIZERS = {"isinstance", "issubclass", "len", "hasattr", "getattr",
               "type", "str", "repr", "id", "callable", "dir", "vars"}

#: attributes that are static under tracing (aval metadata, not data)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "context", "ctx", "name"}

#: tensor methods that force a host scalar (MX203 when receiver is traced)
_SCALARIZERS = {"item", "asscalar"}

#: tensor methods that force a host array (MX205 when receiver is traced)
_HOSTIFIERS = {"asnumpy", "tolist"}

_FORWARD_METHODS = {"forward", "hybrid_forward"}


def _base_names(cls: ast.ClassDef) -> List[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def _hybrid_classes(tree: ast.Module) -> List[ast.ClassDef]:
    """ClassDefs deriving (transitively, within this file) from
    HybridBlock. Plain ``Block`` forwards run eagerly and may use numpy
    freely, so only the hybridizable lineage is linted."""
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    hybrid: Set[str] = {"HybridBlock"}
    changed = True
    while changed:
        changed = False
        for c in classes:
            if c.name not in hybrid and any(b in hybrid
                                            for b in _base_names(c)):
                hybrid.add(c.name)
                changed = True
    return [c for c in classes if c.name in hybrid and c.name != "HybridBlock"]


def _numpy_bindings(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(module aliases bound to numpy, names imported from numpy)."""
    mods: Set[str] = set()
    funcs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy" or a.name.startswith("numpy."):
                    mods.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "numpy"
                                or node.module.startswith("numpy.")):
                for a in node.names:
                    funcs.add(a.asname or a.name)
    return mods, funcs


class _MethodLinter:
    """Single-pass taint walk over one forward/hybrid_forward body."""

    def __init__(self, filename: str, cls: str, fn: ast.FunctionDef,
                 np_mods: Set[str], np_funcs: Set[str],
                 report: Report, hybrid: bool):
        self.filename = filename
        self.where = f"{cls}.{fn.name}"
        self.np_mods = np_mods
        self.np_funcs = np_funcs
        self.report = report
        self.tainted: Set[str] = set()
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args
                 + args.kwonlyargs]
        skip = {"self"}
        if fn.name == "hybrid_forward" and len(names) >= 2:
            skip.add(names[1])  # F — the nd/sym namespace, not a tensor
        # defaulted params are config kwargs, not tensors: _call_cached_op
        # folds non-NDArray args into the static cache key, so
        # `forward(self, x, training=True)` never traces `training` (the
        # same heuristic the nested-def branch applies)
        pos = args.posonlyargs + args.args
        n_def = len(args.defaults)
        for a in pos[len(pos) - n_def:] if n_def else ():
            skip.add(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                skip.add(a.arg)
        for n in names:
            if n not in skip:
                self.tainted.add(n)
        #: tainted names known to be Python containers *holding* tracers
        #: (the *args tuple, list literals of tensors): truthiness/len of
        #: the container itself never touches a tracer, so MX204 must not
        #: fire on `if args:` — only element access re-enters taint.
        self.containers: Set[str] = set()
        for va in (args.vararg, args.kwarg):
            if va is not None:
                self.tainted.add(va.arg)
                self.containers.add(va.arg)
        self.hybrid = hybrid

    # -- reporting ------------------------------------------------------
    def _diag(self, code: str, message: str, node: ast.AST) -> None:
        self.report.add(Diagnostic(
            code, message,
            node=f"{self.filename}:{getattr(node, 'lineno', 0)}",
            op=self.where, pass_name="tracer_lint"))

    # -- taint of an expression ----------------------------------------
    def taints(self, e: Optional[ast.AST]) -> bool:
        if e is None or isinstance(e, (ast.Constant, ast.Lambda,
                                       ast.JoinedStr, ast.FormattedValue)):
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self.taints(e.value)
        if isinstance(e, ast.Subscript):
            return self.taints(e.value)
        if isinstance(e, ast.Call):
            if isinstance(e.func, ast.Name) and (
                    e.func.id in _SANITIZERS
                    or e.func.id in ("float", "bool", "int")):
                return False  # result is host data (misuse flagged apart)
            parts = list(e.args) + [k.value for k in e.keywords]
            if isinstance(e.func, ast.Attribute):
                parts.append(e.func.value)
            return any(self.taints(p) for p in parts)
        if isinstance(e, ast.BinOp):
            return self.taints(e.left) or self.taints(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.taints(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self.taints(v) for v in e.values)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in e.ops):
                return False  # identity/membership, not a tensor compare
            return any(self.taints(x) for x in [e.left] + e.comparators)
        if isinstance(e, ast.IfExp):
            return self.taints(e.body) or self.taints(e.orelse)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.taints(x) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(self.taints(v) for v in e.values if v is not None)
        if isinstance(e, ast.Starred):
            return self.taints(e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return any(self.taints(g.iter) for g in e.generators) \
                or self.taints(e.elt)
        if isinstance(e, ast.DictComp):
            return any(self.taints(g.iter) for g in e.generators) \
                or self.taints(e.value)
        return False  # conservative: unknown constructs don't taint

    def _container_truth(self, test: ast.AST) -> bool:
        """`if args:` / `if not ys:` where the name is a known container of
        traced values — truthiness of the container is host data."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._container_truth(test.operand)
        return isinstance(test, ast.Name) and test.id in self.containers

    def _is_numpy_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in self.np_funcs
        root = f
        while isinstance(root, ast.Attribute):
            root = root.value
        return isinstance(root, ast.Name) and root.id in self.np_mods

    # -- per-statement checks ------------------------------------------
    @staticmethod
    def _own_exprs(stmt: ast.stmt) -> List[ast.AST]:
        """The expressions evaluated by this statement itself — compound
        bodies are linted by recursion with their own (updated) taint
        state, so only headers are inspected here."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, ast.For):
            return [stmt.iter]
        if isinstance(stmt, ast.With):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, ast.FunctionDef):
            return list(stmt.args.defaults) + list(stmt.args.kw_defaults)
        if isinstance(stmt, ast.Try):
            return []
        return [stmt]

    def _check_calls(self, stmt: ast.stmt) -> None:
        for e in [w for x in self._own_exprs(stmt) if x is not None
                  for w in ast.walk(x)]:
            if isinstance(e, ast.IfExp) and self.taints(e.test) \
                    and not self._container_truth(e.test):
                self._diag("MX204", "ternary on a traced value; tracers "
                           "have no truth value — use F.where / lax.cond", e)
            if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                              ast.DictComp)):
                # comp targets bound from a tainted iterable are tainted
                # while we judge that generator's `if` clauses
                comp_vars = {n.id for g in e.generators if self.taints(g.iter)
                             for n in ast.walk(g.target)
                             if isinstance(n, ast.Name)}
                for g in e.generators:
                    for cond in g.ifs:
                        if self._container_truth(cond):
                            continue
                        if self.taints(cond) or any(
                                isinstance(n, ast.Name) and n.id in comp_vars
                                for n in ast.walk(cond)):
                            self._diag("MX204", "comprehension `if` on a "
                                       "traced value; tracers have no truth "
                                       "value — use F.where / lax.cond", e)
            if not isinstance(e, ast.Call):
                continue
            arg_tainted = any(self.taints(a) for a in e.args) or any(
                self.taints(k.value) for k in e.keywords)
            if isinstance(e.func, ast.Name):
                if e.func.id == "print" and arg_tainted:
                    self._diag("MX202", "print() on a traced value runs "
                               "once at trace time; use jax.debug.print or "
                               "a Monitor", e)
                elif e.func.id in ("float", "bool", "int") and arg_tainted:
                    self._diag("MX203", f"{e.func.id}() concretizes a "
                               "traced value (ConcretizationTypeError "
                               "under jit)", e)
            if isinstance(e.func, ast.Attribute) and self.taints(e.func.value):
                if e.func.attr in _SCALARIZERS:
                    self._diag("MX203", f".{e.func.attr}() concretizes a "
                               "traced value to a host scalar", e)
                elif e.func.attr in _HOSTIFIERS:
                    self._diag("MX205", f".{e.func.attr}() pulls a traced "
                               "value to the host; keep compute in F/jnp",
                               e)
            if self._is_numpy_call(e) and arg_tainted:
                self._diag("MX205", "host numpy call on a traced value "
                           "breaks under jit; use the F namespace / "
                           "jax.numpy", e)

    def _assign_target(self, tgt: ast.AST, tainted: bool,
                       stmt: ast.stmt) -> None:
        if isinstance(tgt, ast.Name):
            if tainted:
                self.tainted.add(tgt.id)
            else:
                self.tainted.discard(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._assign_target(elt, tainted, stmt)
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, tainted, stmt)
        elif isinstance(tgt, ast.Attribute):
            root = tgt
            while isinstance(root, ast.Attribute):
                root = root.value
            if tainted and isinstance(root, ast.Name) and root.id == "self" \
                    and self.hybrid:
                self._diag("MX206", f"traced value stored on self."
                           f"{tgt.attr} escapes the trace (leaked tracer: "
                           "UnexpectedTracerError on reuse)", stmt)

    def run(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        self._check_calls(stmt)
        if isinstance(stmt, ast.Assign):
            t = self.taints(stmt.value)
            is_cont = isinstance(stmt.value, (
                ast.Tuple, ast.List, ast.Set, ast.Dict, ast.ListComp,
                ast.SetComp, ast.DictComp, ast.GeneratorExp))
            for tgt in stmt.targets:
                self._assign_target(tgt, t, stmt)
                if isinstance(tgt, ast.Name):
                    if t and is_cont:
                        self.containers.add(tgt.id)
                    else:
                        self.containers.discard(tgt.id)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign_target(stmt.target, self.taints(stmt.value), stmt)
        elif isinstance(stmt, ast.AugAssign):
            t = self.taints(stmt.value) or self.taints(stmt.target)
            self._assign_target(stmt.target, t, stmt)
        elif isinstance(stmt, (ast.If, ast.While)):
            if self.taints(stmt.test) \
                    and not self._container_truth(stmt.test):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self._diag("MX204", f"Python `{kind}` on a traced value; "
                           "tracers have no truth value — use F.where / "
                           "lax.cond / lax.while_loop", stmt)
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            if self.taints(stmt.test) \
                    and not self._container_truth(stmt.test):
                self._diag("MX204", "assert on a traced value; use "
                           "checkify or a static shape check", stmt)
        elif isinstance(stmt, ast.For):
            self._assign_target(stmt.target, self.taints(stmt.iter), stmt)
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars,
                                        self.taints(item.context_expr), stmt)
            self.run(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.run(stmt.body)
            for h in stmt.handlers:
                self.run(h.body)
            self.run(stmt.orelse)
            self.run(stmt.finalbody)
        elif isinstance(stmt, ast.FunctionDef):
            # nested helper traced by the enclosing forward (jax.checkpoint
            # bodies etc.): its params carry trace values unless defaulted
            # to something static
            inner = set(self.tainted)
            for a in stmt.args.args + stmt.args.posonlyargs:
                inner.add(a.arg)
            n_def = len(stmt.args.defaults)
            if n_def:
                pos = (stmt.args.posonlyargs + stmt.args.args)[-n_def:]
                for a, d in zip(pos, stmt.args.defaults):
                    if not self.taints(d):
                        inner.discard(a.arg)
            saved = self.tainted
            self.tainted = inner
            try:
                self.run(stmt.body)
            finally:
                self.tainted = saved

    # note: _check_calls walks the whole statement including nested defs,
    # but call-site taint there uses the *outer* scope; the nested-def
    # branch above re-lints the body with inner seeds. A duplicate
    # diagnostic for the same (code, line) is deduped in lint_source.


def lint_source(src: str, filename: str = "<string>") -> Report:
    """Lint one Python source blob; returns a Report of MX2xx findings."""
    report = Report()
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        report.add(Diagnostic("MX200",
                              f"file does not parse: {e.msg}",
                              node=f"{filename}:{e.lineno or 0}",
                              op="<syntax>", pass_name="tracer_lint"))
        return report
    np_mods, np_funcs = _numpy_bindings(tree)
    raw = Report()
    for cls in _hybrid_classes(tree):
        for item in cls.body:
            if isinstance(item, ast.FunctionDef) \
                    and item.name in _FORWARD_METHODS:
                linter = _MethodLinter(filename, cls.name, item, np_mods,
                                       np_funcs, raw, hybrid=True)
                linter.run(item.body)
    seen = set()
    for d in raw.diagnostics:
        key = (d.code, d.node, d.op)
        if key not in seen:
            seen.add(key)
            report.add(d)
    return report


def lint_file(path: str) -> Report:
    with open(path) as f:
        src = f.read()
    return lint_source(src, filename=path)


def lint_paths(paths) -> Report:
    """Lint files and directories (recursing into ``*.py``)."""
    return walk_lint(paths, lint_file)

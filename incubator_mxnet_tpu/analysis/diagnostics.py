"""Diagnostic records shared by every analysis pass.

Reference counterpart: the error strings nnvm passes throw from
``InferShape``/``InferType``/graph validation (``src/nnvm/``,
``CHECK``/``LOG(FATAL)`` with node context). Here diagnostics are *data*
rather than exceptions: every pass appends :class:`Diagnostic` rows carrying
a stable machine-readable code plus node provenance, and a :class:`Report`
aggregates them for programmatic use (``mx.analysis.verify``) and for the
``mxlint`` CLI exit code.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["Diagnostic", "Report", "CODES", "walk_lint"]


def walk_lint(paths, lint_file) -> "Report":
    """THE file walker every source-lint family shares (tracer MX2xx,
    fault MX4xx, and the combined ``mx.analysis.lint_paths``): files and
    directories, recursing into ``*.py``, merged into one Report."""
    import os
    report = Report()
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _, files in os.walk(p):
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        report.extend(lint_file(os.path.join(dirpath,
                                                             fname)))
        else:
            report.extend(lint_file(p))
    return report

#: Stable diagnostic codes. The MX0xx family is graph structure, MX1xx is
#: abstract shape/dtype evaluation, MX2xx is jit-cache/tracer hygiene,
#: MX3xx is sharding consistency, MX4xx is fault-tolerance hygiene, and
#: MX5xx is serving hygiene (jit-per-request / unbucketed shapes).
#: Codes are append-only: tools and CI grep for them, so a code's meaning
#: never changes once released.
CODES = {
    "MX001": "graph contains a cycle",
    "MX002": "duplicate node name",
    "MX003": "unknown operator (not in the op registry)",
    "MX004": "input arity mismatch vs the registered operator",
    "MX005": "attribute rejected by the operator's declared Schema",
    "MX006": "JSON serialization does not round-trip stably",
    "MX007": "file is not valid JSON or failed to load as a symbol graph",
    "MX008": "multi-output slice index out of range for its base node",
    "MX101": "abstract shape/dtype evaluation failed",
    "MX200": "source file does not parse (nothing in it can be linted)",
    "MX201": "recompilation hazard: jit cache holds many distinct signatures",
    "MX202": "print() on a traced value inside a hybridized forward",
    "MX203": "float()/bool()/int() forces a traced value to a Python scalar",
    "MX204": "Python control flow (if/while/assert) on a traced value",
    "MX205": "host numpy call on a traced value",
    "MX206": "traced value stored on self during trace (leaked tracer)",
    "MX301": "PartitionSpec names a mesh axis the mesh does not declare",
    "MX302": "PartitionSpec rank/divisibility mismatch with the parameter",
    "MX303": "conflicting PartitionSpecs match the same parameter",
    "MX401": "training loop never checkpoints (no save_checkpoint/"
             "save_states/save_parameters call; a crash loses the run)",
    "MX501": "inference path compiles/re-traces inside the request loop "
             "(jit/hybridize/CompiledModel per iteration)",
    "MX502": "serving entry point jits on raw (unbucketed) request shapes "
             "— every novel shape is a fresh XLA compile",
    "MX601": "training loop / serving entry point builds ad-hoc timing or "
             "counters instead of mx.telemetry (invisible to the unified "
             "event bus, metrics scrape, and snapshot)",
}


@dataclass
class Diagnostic:
    """One finding: a stable code, a human message, and where it happened.

    ``node`` is the graph-node name (or ``file:line`` for source lints),
    ``op`` the operator name (or ``Class.method`` for source lints), and
    ``attrs`` the offending node's public attribute dict — the same
    provenance triple the shape checker threads through
    :class:`~incubator_mxnet_tpu.symbol.GraphInferenceError`.
    """

    code: str
    message: str
    node: Optional[str] = None
    op: Optional[str] = None
    attrs: Optional[dict] = None
    pass_name: str = ""
    severity: str = "error"  # "error" | "warning"

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code!r}; "
                             f"register it in analysis.diagnostics.CODES")

    def __str__(self):
        where = self.node or "<graph>"
        op = f" (op {self.op!r})" if self.op else ""
        return f"{where}: {self.code}{op}: {self.message}"


@dataclass
class Report:
    """Ordered diagnostics from one analysis run."""

    diagnostics: List[Diagnostic] = field(default_factory=list)
    #: passes that could not run (e.g. shape pass without input shapes)
    skipped: List[str] = field(default_factory=list)

    def add(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def extend(self, other: "Report") -> None:
        self.diagnostics.extend(other.diagnostics)
        self.skipped.extend(other.skipped)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def raise_if_errors(self) -> "Report":
        if self.errors:
            from ..base import MXNetError
            raise MXNetError(
                "graph verification failed:\n" +
                "\n".join(f"  {d}" for d in self.errors))
        return self

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __str__(self):
        if not self.diagnostics:
            return "clean (0 diagnostics)"
        return "\n".join(str(d) for d in self.diagnostics)

"""Diagnostic records shared by every analysis pass.

Reference counterpart: the error strings nnvm passes throw from
``InferShape``/``InferType``/graph validation (``src/nnvm/``,
``CHECK``/``LOG(FATAL)`` with node context). Here diagnostics are *data*
rather than exceptions: every pass appends :class:`Diagnostic` rows carrying
a stable machine-readable code plus node provenance, and a :class:`Report`
aggregates them for programmatic use (``mx.analysis.verify``) and for the
``mxlint`` CLI exit code.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["Diagnostic", "Report", "CODES", "DEFAULT_SEVERITY",
           "default_severity", "walk_lint", "parse_suppressions",
           "apply_suppressions"]


def walk_lint(paths, lint_file) -> "Report":
    """THE file walker every source-lint family shares (tracer MX2xx,
    fault MX4xx, and the combined ``mx.analysis.lint_paths``): files and
    directories, recursing into ``*.py``, merged into one Report."""
    import os
    report = Report()
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _, files in os.walk(p):
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        report.extend(lint_file(os.path.join(dirpath,
                                                             fname)))
        else:
            report.extend(lint_file(p))
    return report

#: Stable diagnostic codes. The MX0xx family is graph structure, MX1xx is
#: abstract shape/dtype evaluation, MX2xx is jit-cache/tracer hygiene,
#: MX3xx is sharding consistency, MX4xx is fault-tolerance hygiene, and
#: MX5xx is serving hygiene (jit-per-request / unbucketed shapes).
#: Codes are append-only: tools and CI grep for them, so a code's meaning
#: never changes once released.
CODES = {
    "MX001": "graph contains a cycle",
    "MX002": "duplicate node name",
    "MX003": "unknown operator (not in the op registry)",
    "MX004": "input arity mismatch vs the registered operator",
    "MX005": "attribute rejected by the operator's declared Schema",
    "MX006": "JSON serialization does not round-trip stably",
    "MX007": "file is not valid JSON or failed to load as a symbol graph",
    "MX008": "multi-output slice index out of range for its base node",
    "MX101": "abstract shape/dtype evaluation failed",
    "MX200": "source file does not parse (nothing in it can be linted)",
    "MX201": "recompilation hazard: jit cache holds many distinct signatures",
    "MX202": "print() on a traced value inside a hybridized forward",
    "MX203": "float()/bool()/int() forces a traced value to a Python scalar",
    "MX204": "Python control flow (if/while/assert) on a traced value",
    "MX205": "host numpy call on a traced value",
    "MX206": "traced value stored on self during trace (leaked tracer)",
    "MX301": "PartitionSpec names a mesh axis the mesh does not declare",
    "MX302": "PartitionSpec rank/divisibility mismatch with the parameter",
    "MX303": "conflicting PartitionSpecs match the same parameter",
    "MX401": "training loop never checkpoints (no save_checkpoint/"
             "save_states/save_parameters call; a crash loses the run)",
    "MX501": "inference path compiles/re-traces inside the request loop "
             "(jit/hybridize/CompiledModel per iteration)",
    "MX502": "serving entry point jits on raw (unbucketed) request shapes "
             "— every novel shape is a fresh XLA compile",
    "MX601": "training loop / serving entry point builds ad-hoc timing or "
             "counters instead of mx.telemetry (invisible to the unified "
             "event bus, metrics scrape, and snapshot)",
    "MX602": "request-path code emits bus events outside any request/step "
             "correlation scope (uncorrelated telemetry — the event can "
             "never be stitched into a request or step story)",
    "MX603": "tensor statistics routed through a host callback inside a "
             "jitted function (jax.debug.callback/print, pure_callback, "
             "io_callback over a reduction) — breaks whole-step capture; "
             "return the stats as extra pinned outputs instead "
             "(telemetry.numerics)",
    "MX604": "stray device sync inside a step loop "
             "(block_until_ready()/.item()/float() on a step result "
             "every iteration) — a second host round trip per step "
             "outside the guard's single-sync cadence; read "
             "trainer.last_loss/last_grad_norm (synced once by the "
             "guard) or decimate the read (if step % N)",
    "MX701": "host<->device transfer inside a jitted region (callback / "
             "device_put round-trip per executed step)",
    "MX702": "unintended f64/widening float promotion in the compiled "
             "graph (strongly-typed scalar or x64 leak)",
    "MX703": "dead compute or unused parameter in the compiled graph "
             "(transferred and compiled, never read by any output)",
    "MX704": "missed buffer-donation opportunity (input dropped after "
             "last read but not donated; an output aval matches)",
    "MX705": "large constant baked into the compiled graph (>1 MiB "
             "literal; should ride as an argument)",
    "MX706": "trace-signature divergence: call sites of one model lower "
             "to different signatures (static twin of the telemetry "
             "compile ledger)",
    "MX707": "informational per-graph cost table entry (FLOPs, bytes, "
             "transcendentals, fusion groups) from analysis.hlo.cost — "
             "never gates a build",
    "MX708": "mesh-configured trainer step breaks the compiled-collective "
             "contract: a per-parameter host round-trip (callback / live "
             "device_put) or a non-donated >=64KiB parameter/optimizer "
             "buffer survives in the step graph",
    "MX709": "peak live device memory over budget: the graph's (or the "
             "bucket ladder's summed) liveness-scan peak_live_bytes "
             "exceeds MXTPU_HBM_BUDGET — the geometry cannot fit on "
             "the chip",
    "MX710": "informational quantized-region summary (quantize boundaries, "
             "int8 matmuls, dequantize boundaries, estimated bytes saved) "
             "from analysis.hlo.quant — provenance row for quantized "
             "serving, never gates a build; emitted only under "
             "verify(..., quant=True)",
    "MX711": "silent f32 promotion inside a declared-int8 region: a "
             "quantized (int8) tensor is widened back to float and feeds "
             "a float matmul/conv — the compute the quantization was "
             "supposed to run on the int8 MXU path silently runs at f32",
    "MX712": "quantized tensor with no calibration provenance: the "
             "quantize boundary's range is computed on the fly from the "
             "data being quantized (an online min/max reduction) instead "
             "of a calibrated Observer range baked into the graph",
    "MX713": "q/dq pairing hazard: a tensor is re-quantized with no "
             "intervening compute (a quantize→dequantize→quantize round "
             "trip / double quantization) — a scale/zero-point mismatch "
             "across the boundary silently degrades accuracy",
    "MX714": "accuracy-hazard reduction kept in int8: an additive "
             "reduction (sum/mean/softmax/normalization accumulation) "
             "runs with an int8 accumulator — 8-bit accumulation "
             "overflows; widen to int32/float before reducing",
    "MX715": "quantization boundary churn: the graph's quantize/"
             "dequantize convert traffic exceeds the f32 bytes its int8 "
             "compute saves — the quantized build is an anti-optimization "
             "(priced via analysis.hlo.cost)",
    "MX801": "shared attribute mutated without the lock that guards it "
             "elsewhere, in a class that runs threads (attribute→lock "
             "binding inferred from `with self._lock:` dominance)",
    "MX802": "lock-order inversion: the static lock-acquisition graph "
             "has a cycle (or a non-reentrant lock re-acquired while "
             "held) — a deadlock waiting for the right interleaving",
    "MX803": "blocking call (socket/queue/sleep/join/XLA compile) while "
             "holding a lock — serializes every other thread behind one "
             "slow operation",
    "MX804": "thread-lifecycle hygiene: threading.Thread without "
             "explicit name=/daemon=, a non-daemon thread never joined, "
             "or start() in __init__ before state is fully assigned",
    "MX805": "jit/bucket compile cache accessed without the owning "
             "class's lock (the caches telemetry.compile_log tracks "
             "must be synchronized wherever threads can reach them)",
    "MX901": "collective-sequence divergence: host-conditional control "
             "flow (a branch on process_index()/process_count()/rank env "
             "vars) encloses a collective issue, jitted-graph "
             "build/dispatch, or kvstore traffic — in the multi-"
             "controller SPMD model the processes that skip the branch "
             "never reach the collective and the pod hangs, not crashes",
    "MX902": "unelected side effect: a multi-host-aware module writes a "
             "persistent file (checkpoint, telemetry export, flight "
             "bundle, artifact cache) with no host-0 election guard — "
             "the inverse rule of MX901: collectives must not diverge "
             "across hosts, filesystem effects must",
    "MX903": "non-elastic world assumption: a mesh shape / world size "
             "frozen from jax.devices()/device_count()/process_count() "
             "or a rank env var at import time (module scope or a "
             "default argument) — the value is baked in before "
             "dist.initialize() can rendezvous, so an elastic restart "
             "with a different topology silently reuses the stale count",
    "MX904": "cross-host RNG divergence: unseeded or time-seeded "
             "randomness in a multi-host-aware module without a "
             "process_index-folded or broadcast seed — each host draws "
             "a different stream, so 'identical' SPMD programs feed "
             "different batches/graphs and the run diverges silently",
    "MX905": "collective-schedule divergence across buckets of one "
             "entry: the traced graphs issue different collective "
             "verb/axis sequences — the static twin of the telemetry "
             "collective ledger's cross-process fingerprint crosscheck",
}

#: Default severity per code — THE single source of truth the passes,
#: the mxlint ``--format=json`` output, and the generated docs share.
#: A pass may still override per finding (e.g. MX302 is an error for a
#: rank mismatch but a warning for an indivisible dim); a
#: :class:`Diagnostic` constructed without an explicit severity takes
#: the registry value. Audited by tests/test_analysis.py: every code has
#: exactly one entry, families are contiguous, values are valid.
DEFAULT_SEVERITY: Dict[str, str] = {
    "MX001": "error", "MX002": "error", "MX003": "error", "MX004": "error",
    "MX005": "error", "MX006": "error", "MX007": "error", "MX008": "error",
    "MX101": "error",
    "MX200": "error", "MX201": "warning", "MX202": "error", "MX203": "error",
    "MX204": "error", "MX205": "error", "MX206": "error",
    "MX301": "error", "MX302": "error", "MX303": "error",
    "MX401": "warning",
    "MX501": "warning", "MX502": "warning",
    "MX601": "warning", "MX602": "warning", "MX603": "warning",
    "MX604": "warning",
    "MX701": "error", "MX702": "warning", "MX703": "warning",
    "MX704": "warning", "MX705": "error", "MX706": "warning",
    "MX707": "info", "MX708": "error", "MX709": "error",
    "MX710": "info", "MX711": "error", "MX712": "error",
    "MX713": "error", "MX714": "warning", "MX715": "warning",
    "MX801": "warning", "MX802": "error", "MX803": "warning",
    "MX804": "warning", "MX805": "warning",
    "MX901": "error", "MX902": "warning", "MX903": "warning",
    "MX904": "warning", "MX905": "error",
}


def default_severity(code: str) -> str:
    return DEFAULT_SEVERITY.get(code, "error")


@dataclass
class Diagnostic:
    """One finding: a stable code, a human message, and where it happened.

    ``node`` is the graph-node name (or ``file:line`` for source lints),
    ``op`` the operator name (or ``Class.method`` for source lints), and
    ``attrs`` the offending node's public attribute dict — the same
    provenance triple the shape checker threads through
    :class:`~incubator_mxnet_tpu.symbol.GraphInferenceError`.
    """

    code: str
    message: str
    node: Optional[str] = None
    op: Optional[str] = None
    attrs: Optional[dict] = None
    pass_name: str = ""
    #: "error" | "warning" | "info"; None = take DEFAULT_SEVERITY[code]
    severity: Optional[str] = None

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code!r}; "
                             f"register it in analysis.diagnostics.CODES")
        if self.severity is None:
            self.severity = default_severity(self.code)

    def __str__(self):
        where = self.node or "<graph>"
        op = f" (op {self.op!r})" if self.op else ""
        return f"{where}: {self.code}{op}: {self.message}"

    def as_dict(self) -> dict:
        """Machine form for ``mxlint --format=json``: one flat object per
        finding. ``file``/``line`` are filled only for path-shaped
        provenance (``file:line`` from source lints, or a lint target
        path) so a CI annotator never targets a nonexistent path;
        graph-shaped provenance (``Model[bucket]``, node names) rides in
        ``node``, which always carries the raw value."""
        node = self.node or ""
        file, line = "", 0
        m = re.match(r"^(.*):(\d+)$", node)
        if m and not m.group(1).startswith("<"):   # '<string>:4' is not a path
            file, line = m.group(1), int(m.group(2))
        elif "/" in node or node.endswith((".py", ".json")):
            file = node           # a lint target path without a line
        return {"file": file, "line": line, "node": node,
                "code": self.code, "severity": self.severity,
                "message": self.message, "pass": self.pass_name,
                "op": self.op}


@dataclass
class Report:
    """Ordered diagnostics from one analysis run."""

    diagnostics: List[Diagnostic] = field(default_factory=list)
    #: passes that could not run (e.g. shape pass without input shapes)
    skipped: List[str] = field(default_factory=list)

    def add(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def extend(self, other: "Report") -> None:
        self.diagnostics.extend(other.diagnostics)
        self.skipped.extend(other.skipped)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def infos(self) -> List[Diagnostic]:
        """Informational rows (MX707 cost tables) — never gate a build."""
        return [d for d in self.diagnostics if d.severity == "info"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def summary_dict(self) -> dict:
        """THE machine summary every staging gate records (the registry's
        ``serve.analysis`` telemetry event, serve_bench's JSON) — one
        projection, so the records can't drift."""
        return {"errors": len(self.errors),
                "warnings": len(self.warnings),
                "codes": sorted({d.code for d in self.diagnostics}),
                "skipped": list(self.skipped)}

    def raise_if_errors(self) -> "Report":
        if self.errors:
            from ..base import MXNetError
            raise MXNetError(
                "graph verification failed:\n" +
                "\n".join(f"  {d}" for d in self.errors))
        return self

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __str__(self):
        if not self.diagnostics:
            return "clean (0 diagnostics)"
        return "\n".join(str(d) for d in self.diagnostics)


# ---------------------------------------------------------------------------
# inline suppressions (the clang-tidy NOLINT analogue)
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*mxlint:\s*disable(-file)?\s*=\s*([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)")


def parse_suppressions(src: str) -> Tuple[Set[str], Dict[int, Set[str]]]:
    """Scan one source blob for ``# mxlint: disable=MXnnn[,MXnnn]``
    (same-line) and ``# mxlint: disable-file=MXnnn[,...]`` (whole file)
    markers. Returns ``(file_level_codes, {lineno: codes})``.

    Only REAL ``#`` comments count — the marker inside a string literal
    or docstring (e.g. documentation *about* suppressions) must not
    disable anything, so the scan tokenizes rather than grepping lines.
    A trailing comment on a statement wrapped across lines registers for
    the whole logical line (AST nodes report the statement's FIRST line;
    the comment sits on the last). A file that cannot be tokenized
    yields no suppressions (its only diagnostic is MX200 anyway)."""
    import io
    import tokenize

    file_level: Set[str] = set()
    by_line: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return file_level, by_line
    _skip = {tokenize.NEWLINE, tokenize.NL, tokenize.COMMENT,
             tokenize.INDENT, tokenize.DEDENT, tokenize.ENCODING}
    logical_start = None
    for tok in tokens:
        if tok.type == tokenize.NEWLINE:
            logical_start = None
        elif logical_start is None and tok.type not in _skip:
            logical_start = tok.start[0]
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        codes = {c.strip() for c in m.group(2).split(",")}
        if m.group(1):
            file_level |= codes
        else:
            for line in {tok.start[0], logical_start or tok.start[0]}:
                by_line.setdefault(line, set()).update(codes)
    return file_level, by_line


def apply_suppressions(report: "Report", src: str) -> "Report":
    """Drop diagnostics whose ``file:line`` provenance carries a matching
    inline suppression. Source-lint families call this once per file so
    framework-internal idioms (reference-parity code the AST rules
    misread) stay annotated in place rather than special-cased in the
    linter."""
    file_level, by_line = parse_suppressions(src)
    if not file_level and not by_line:
        return report
    kept = Report(skipped=list(report.skipped))
    for d in report.diagnostics:
        m = re.match(r"^.*:(\d+)$", d.node or "")
        line = int(m.group(1)) if m else 0
        if d.code in file_level or d.code in by_line.get(line, ()):
            continue
        kept.add(d)
    return kept

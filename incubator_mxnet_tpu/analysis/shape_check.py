"""Abstract shape/dtype checker (pass 2).

Reference counterpart: the nnvm ``InferShape``/``InferType`` passes
(SURVEY §2.2) which walk the graph propagating shapes and fail with the
offending node. Here the walk is ``jax.eval_shape`` over the same evaluator
the executor uses (``symbol._infer_graph_shapes``): every op's abstract
evaluation is free, and a failure surfaces as
:class:`~incubator_mxnet_tpu.symbol.GraphInferenceError` carrying node
provenance (node name, op name, public attrs) instead of a raw JAX
traceback. This pass converts that into an **MX101** diagnostic.

The pass needs input shapes (``PassContext.shapes``). When they are absent
and the graph has unresolved data variables, the pass records itself as
skipped rather than failing — shape checking without shapes is not a graph
error.
"""
from __future__ import annotations

from .passes import PassContext, register_pass

__all__ = ["check_shapes"]


@register_pass("infer_shapes",
               describe="whole-graph jax.eval_shape walk with node "
                        "provenance (MX101)")
def check_shapes(ctx: PassContext) -> None:
    from ..base import MXNetError
    from ..symbol import GraphInferenceError

    if any(d.code == "MX001" for d in ctx.report.diagnostics):
        # structural validity gates semantic passes (the nnvm pass-dependency
        # rule): a cyclic graph has no topological walk to evaluate
        ctx.report.skipped.append("infer_shapes: graph is cyclic (MX001)")
        return
    shapes = ctx.shapes or {}
    try:
        ctx.sym.infer_shape(**{k: tuple(v) for k, v in shapes.items()})
    except GraphInferenceError as e:
        ctx.diag("MX101", e.reason, node=e.node_name, op=e.op,
                 attrs=e.attrs, pass_name="infer_shapes")
    except MXNetError as e:
        # unresolved input shapes / unknown op: owned by graph_verify or
        # by the caller not supplying shapes — not a shape-semantics error
        ctx.report.skipped.append(f"infer_shapes: {e}")

"""``mx.analysis`` — static graph verification and JAX-pitfall linting.

Reference counterpart: the correctness half of the nnvm pass infrastructure
(``InferShape``/``InferType``, op-attr validation via ``dmlc::Parameter``,
graph JSON checks) that rejected malformed programs before execution
(SURVEY §2.2/§2.4) — generalized with the checks a JAX graft newly needs:
tracer-leak linting, jit-recompilation accounting, and sharding/mesh
consistency. Four pass families over one registry
(:mod:`~incubator_mxnet_tpu.analysis.passes`, the ``NNVM_REGISTER_PASS``
analogue):

========================  ===========================================
``graph_verify``          structure/registry/Schema/round-trip, MX0xx
``infer_shapes``          abstract eval with provenance, MX1xx
tracer lint + recompile   jit hygiene (AST + runtime), MX2xx
``sharding``              PartitionSpec vs mesh, MX3xx
fault lint                checkpoint hygiene (AST), MX4xx
serve lint                serving/jit-cache hygiene (AST), MX5xx
telemetry lint            observability hygiene (AST), MX6xx
``hlo`` passes            compiled-graph (jaxpr/StableHLO), MX7xx
``concurrency`` passes    race/deadlock/lock-order (AST, whole-package
                          lock graph + runtime sanitizer twin), MX8xx
``distributed`` passes    SPMD divergence hazards (AST + HLO, runtime
                          collective-ledger twin), MX9xx
========================  ===========================================

Source lints honor inline suppressions (``# mxlint: disable=MX204`` on
the flagged line, ``# mxlint: disable-file=MX501`` anywhere) so
reference-parity idioms the AST rules misread are annotated in place.

Programmatic entry point::

    report = mx.analysis.verify(sym, shapes={"data": (32, 784)})
    report.raise_if_errors()

CLI (models, examples and saved symbol JSON)::

    python -m tools.mxlint incubator_mxnet_tpu/models examples net.json
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from .diagnostics import (  # noqa: F401
    CODES, DEFAULT_SEVERITY, Diagnostic, Report, apply_suppressions,
    default_severity, parse_suppressions,
)
from .passes import (  # noqa: F401
    PASSES, GraphPass, PassContext, get_pass, list_passes, register_pass,
    run_passes,
)
from . import graph_verifier  # noqa: F401  (registers graph_verify)
from . import shape_check  # noqa: F401  (registers infer_shapes)
from . import sharding_check  # noqa: F401  (registers sharding)
from .graph_verifier import tensor_arity  # noqa: F401
from .sharding_check import check_sharding  # noqa: F401
from . import fault_lint  # noqa: F401
from . import serve_lint  # noqa: F401
from . import telemetry_lint  # noqa: F401
from . import tracer_lint  # noqa: F401
from .recompile import (  # noqa: F401
    RECOMPILE_WARN_THRESHOLD, RecompileWarning, cache_report, note_compile,
)
from . import hlo  # noqa: F401  (registers the MX7xx compiled-graph passes)
from . import concurrency  # noqa: F401  (MX8xx + the lockcheck twin)
from . import distributed  # noqa: F401  (MX9xx + the collective-ledger twin)


def lint_source(src, filename: str = "<string>") -> Report:
    """Source lint = tracer hygiene (MX2xx) + fault hygiene (MX4xx) +
    serving hygiene (MX5xx) + observability hygiene (MX6xx), one merged
    Report (the ``mxlint`` Python-target entry point). Inline
    ``# mxlint: disable=`` markers are applied once, here, for every
    family."""
    report = tracer_lint.lint_source(src, filename)
    report.extend(fault_lint.lint_source(src, filename))
    report.extend(serve_lint.lint_source(src, filename))
    report.extend(telemetry_lint.lint_source(src, filename))
    return apply_suppressions(report, src)


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_paths(paths) -> Report:
    """Lint files and directories (recursing into ``*.py``) with every
    source-lint family."""
    from .diagnostics import walk_lint
    return walk_lint(paths, lint_file)

__all__ = ["verify", "Report", "Diagnostic", "CODES", "DEFAULT_SEVERITY",
           "default_severity", "register_pass",
           "list_passes", "run_passes", "PassContext", "tensor_arity",
           "check_sharding", "lint_source", "lint_file", "lint_paths",
           "cache_report", "RecompileWarning", "RECOMPILE_WARN_THRESHOLD",
           "hlo", "concurrency", "distributed", "parse_suppressions",
           "apply_suppressions"]


def verify(sym, shapes: Optional[Dict[str, tuple]] = None,
           rules=None, mesh=None,
           params: Optional[Dict[str, tuple]] = None,
           passes: Optional[Sequence[str]] = None) -> Report:
    """Run the analysis passes over one Symbol and return the
    :class:`Report` (``report.ok`` / ``report.raise_if_errors()``).

    ``shapes`` feeds the ``infer_shapes`` pass (it is skipped when the
    graph has data variables with no shape given); ``rules`` + ``mesh``
    (+ optional ``params`` name->shape) activate the ``sharding`` pass.
    ``passes`` selects a subset by name (default: all registered).
    """
    return run_passes(sym, names=passes, shapes=shapes, rules=rules,
                      mesh=mesh, params=params)

"""MX7xx inspection passes over traced compiled graphs.

Each pass is ``fn(HloPassContext) -> None`` over the full list of
:class:`~.trace.TracedGraph` records (MX706 needs the cross-site view;
the others iterate per graph), appending
:class:`~..diagnostics.Diagnostic` rows. Registered in ``HLO_PASSES`` —
the compiled-graph sibling of the Symbol pass registry in
``analysis/passes.py``.

==========  =============================================================
``MX701``   host↔device round-trip inside the jitted region (callbacks;
            ``device_put`` hints as warnings)
``MX702``   unintended f64 / widening float promotion in an inference
            graph (the classic strong-``np.float32``-scalar leak)
``MX703``   dead compute and unused parameters (wasted transfer + FLOPs)
``MX704``   droppable input buffer not donated though an output aval
            matches (serve request buffers, optimizer states)
``MX705``   large constant baked into the graph (>1 MiB literal)
``MX706``   trace-signature divergence across call sites — the static
            twin of the telemetry compile ledger
``MX709``   peak live device memory (liveness scan, ``cost.py``) over
            ``MXTPU_HBM_BUDGET`` — per graph and per bucket ladder
==========  =============================================================
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as onp

from ..diagnostics import Diagnostic, Report
from .trace import TracedGraph, _jaxprs_in, _sig_str, walk_eqns

__all__ = ["HLO_PASSES", "HloPassContext", "register_hlo_pass",
           "list_hlo_passes", "run_hlo_passes"]

#: callback primitives = a host round-trip per executed step
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback", "outside_call", "host_callback_call"}
#: transfer hints worth a warning (placement churn inside jit); plain
#: `copy` is a device-local buffer copy XLA elides, so it is NOT here
_TRANSFER_PRIMS = {"device_put"}


@dataclass
class HloPassContext:
    graphs: List[TracedGraph]
    report: Report = field(default_factory=Report)
    #: knobs: const_limit_bytes, donation_min_bytes
    options: Dict[str, object] = field(default_factory=dict)
    #: set by run_hlo_passes around each pass (context-local, so
    #: concurrent verify() calls can't corrupt each other's provenance)
    pass_name: str = ""

    def opt(self, name: str, default):
        return self.options.get(name, default)

    def diag(self, code: str, message: str, graph: TracedGraph = None,
             op: Optional[str] = None, severity: Optional[str] = None,
             node: Optional[str] = None) -> None:
        self.report.add(Diagnostic(
            code, message, node=node or (graph.label if graph else None),
            op=op, pass_name=self.pass_name, severity=severity))


@dataclass
class HloPass:
    name: str
    fn: Callable[[HloPassContext], None]
    describe: str = ""

    def __call__(self, ctx: HloPassContext) -> None:
        self.fn(ctx)


HLO_PASSES: "OrderedDict[str, HloPass]" = OrderedDict()


def register_hlo_pass(name: Optional[str] = None, describe: str = ""):
    def _do(fn):
        pname = name or fn.__name__
        HLO_PASSES[pname] = HloPass(
            pname, fn, describe or (fn.__doc__ or "").split("\n")[0])
        return fn
    return _do


def list_hlo_passes() -> List[str]:
    return list(HLO_PASSES)


def run_hlo_passes(graphs: List[TracedGraph], names=None,
                   **options) -> Report:
    ctx = HloPassContext(list(graphs), options=options)
    for name in (names if names is not None else list_hlo_passes()):
        if name not in HLO_PASSES:
            from ...base import MXNetError
            raise MXNetError(f"unknown hlo pass {name!r}; registered: "
                             f"{list_hlo_passes()}")
        ctx.pass_name = name
        try:
            HLO_PASSES[name](ctx)
        finally:
            ctx.pass_name = ""
    return ctx.report


# ---------------------------------------------------------------------------
# jaxpr utilities
# ---------------------------------------------------------------------------

def _is_literal(v) -> bool:
    return type(v).__name__ == "Literal"


def _np_dtype(dtype):
    """numpy dtype or None (extended dtypes like PRNG keys don't map)."""
    try:
        return onp.dtype(dtype)
    except TypeError:
        return None


def _float_bits(dtype) -> int:
    d = _np_dtype(dtype)
    if d is None:
        return 0
    if d.kind == "f":
        return d.itemsize * 8
    if d.kind == "c":
        return d.itemsize * 4            # per-component width
    return 0


def _liveness(jaxpr):
    """Backward sweep: (needed var set, dead eqn list). Effectful eqns are
    always live; literals never carry liveness."""
    needed = {v for v in jaxpr.outvars if not _is_literal(v)}
    dead = []
    for eqn in reversed(jaxpr.eqns):
        if getattr(eqn, "effects", None) or any(
                o in needed for o in eqn.outvars):
            for iv in eqn.invars:
                if not _is_literal(iv):
                    needed.add(iv)
        else:
            dead.append(eqn)
    return needed, list(reversed(dead))


def _key_reach(jaxpr, seed_invars):
    """Vars tainted by RNG-key / step-counter plumbing (``random_wrap`` of
    an unused key, ``fold_in(key, t)``, dtype converts of ``t``) — dead
    eqns whose outputs all live here are bookkeeping, not wasted model
    compute."""
    reach = set(seed_invars)
    for eqn in jaxpr.eqns:
        if any(not _is_literal(v) and v in reach for v in eqn.invars):
            reach.update(eqn.outvars)
    return reach


# ---------------------------------------------------------------------------
# MX701 — host transfer inside the jitted region
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_transfer",
                   describe="host↔device transfer inside a jitted region "
                            "(callbacks, device_put), MX701")
def hlo_transfer(ctx: HloPassContext) -> None:
    def scan(jaxpr, live, cbs, moves):
        # forward reach from the invars: a device_put of a *constant* is
        # materialization XLA hoists once, not a per-step transfer — only
        # moves of live (invar-derived) data count. Sub-jaxprs (scan/cond
        # bodies) are entered with their invars live whenever the
        # enclosing eqn consumes live data (conservative).
        reach = set(live)
        for eqn in jaxpr.eqns:
            live_in = any(not _is_literal(v) and v in reach
                          for v in eqn.invars)
            if live_in:
                reach.update(eqn.outvars)
            name = eqn.primitive.name
            if name in _CALLBACK_PRIMS or name.endswith("_callback"):
                cbs.append(eqn)
            elif name in _TRANSFER_PRIMS and live_in:
                moves.append(eqn)
            for v in eqn.params.values():
                for sub in _jaxprs_in(v):
                    scan(sub, set(sub.invars) if live_in else set(),
                         cbs, moves)

    for g in ctx.graphs:
        cbs, moves = [], []
        scan(g.closed.jaxpr, set(g.closed.jaxpr.invars), cbs, moves)
        for eqn in cbs[:3]:
            ctx.diag("MX701",
                     f"'{eqn.primitive.name}' inside the compiled graph: "
                     "every executed step round-trips to the host "
                     "(device→host sync + Python + host→device) — move "
                     "the computation into the graph or outside the jit "
                     "boundary", g, op=eqn.primitive.name, severity="error")
        if len(cbs) > 3:
            ctx.diag("MX701", f"{len(cbs) - 3} more host-callback site(s) "
                     "in the same graph", g, severity="error")
        for eqn in moves[:1]:
            ctx.diag("MX701",
                     f"'{eqn.primitive.name}' inside the compiled graph "
                     f"({len(moves)} site(s)): placement/layout churn the "
                     "compiler must materialize — prefer sharding "
                     "constraints or pre-placing inputs", g,
                     op=eqn.primitive.name, severity="warning")


# ---------------------------------------------------------------------------
# MX702 — unintended f64 / widening promotion
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_promotion",
                   describe="unintended f64/widening float promotion, MX702")
def hlo_promotion(ctx: HloPassContext) -> None:
    for g in ctx.graphs:
        jaxpr = g.closed.jaxpr
        in_bits = [_float_bits(v.aval.dtype)
                   for v, r in zip(jaxpr.invars, g.roles)
                   if r in ("input", "param") and hasattr(v.aval, "dtype")]
        max_in = max([b for b in in_bits if b], default=0)
        f64 = None
        for eqn in walk_eqns(jaxpr):
            for o in eqn.outvars:
                d = _np_dtype(o.aval.dtype) \
                    if hasattr(o.aval, "dtype") else None
                if d is not None and d.name in ("float64", "complex128"):
                    f64 = eqn
                    break
            if f64 is not None:
                break
        if f64 is not None and max_in < 64:
            ctx.diag("MX702",
                     f"'{f64.primitive.name}' produces float64 but no "
                     "model input/parameter is 64-bit: an accidental "
                     "x64 promotion doubles memory traffic and falls off "
                     "the TPU fast path", g, op=f64.primitive.name,
                     severity="error")
            continue
        if g.kind != "infer" or max_in == 0:
            continue         # train graphs upcast deliberately (fp32 master)
        wide = []
        for eqn in walk_eqns(jaxpr):
            for o in eqn.outvars:
                bits = _float_bits(o.aval.dtype) \
                    if hasattr(o.aval, "dtype") else 0
                if bits > max_in:
                    wide.append((eqn, bits))
                    break
        if wide:
            eqn, bits = wide[0]
            ctx.diag("MX702",
                     f"'{eqn.primitive.name}' widens to float{bits} in a "
                     f"float{max_in} graph ({len(wide)} eqn(s) run at the "
                     "wider dtype): a strongly-typed scalar/constant "
                     "(np.float32(...) instead of a Python float) promotes "
                     "every downstream op — use weak Python scalars or "
                     "cast the constant", g, op=eqn.primitive.name,
                     severity="warning")


# ---------------------------------------------------------------------------
# MX703 — dead outputs / unused parameters
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_dead_code",
                   describe="dead compute and unused parameters/inputs, "
                            "MX703")
def hlo_dead_code(ctx: HloPassContext) -> None:
    for g in ctx.graphs:
        jaxpr = g.closed.jaxpr
        needed, dead = _liveness(jaxpr)
        seeds = [v for v, r in zip(jaxpr.invars, g.roles)
                 if r in ("rng_key", "other")]
        ignorable = _key_reach(jaxpr, seeds)
        dead = [e for e in dead
                if not all(o in ignorable for o in e.outvars)]
        if dead:
            prims = ", ".join(sorted({e.primitive.name for e in dead})[:4])
            ctx.diag("MX703",
                     f"{len(dead)} eqn(s) compute values no output needs "
                     f"({prims}): dead compute bloats the executable and "
                     "compile time even when XLA elides it", g, op=prims,
                     severity="warning")
        for v, name, role in zip(jaxpr.invars, g.arg_names, g.roles):
            if role == "rng_key" or v in needed:
                continue
            what = "parameter" if role in ("param", "state") else "input"
            ctx.diag("MX703",
                     f"{what} '{name}' is never read by the graph: it is "
                     "still transferred and held on device every call — "
                     "drop it from the signature or the parameter set", g,
                     op=name, severity="warning")


# ---------------------------------------------------------------------------
# MX704 — missed buffer-donation opportunity
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_donation",
                   describe="droppable input buffer not donated though an "
                            "output aval matches, MX704")
def hlo_donation(ctx: HloPassContext) -> None:
    min_bytes = int(ctx.opt("donation_min_bytes", 1 << 16))
    seen = set()             # one finding per (entry, input) across buckets
    for g in ctx.graphs:
        if g.donated is None:
            continue          # no donation info (bare block / artifact)
        # infer graphs: request buffers (role "input") are the droppable
        # ones. Train graphs: the params/optimizer states the step
        # returns updated copies of — a trainer built with donate=False
        # allocates a second full model's worth of buffers per step.
        droppable = ("input",) if g.kind != "train" \
            else ("param", "state", "input")
        jaxpr = g.closed.jaxpr
        out_sigs = set()
        for o in jaxpr.outvars:
            aval = getattr(o, "aval", None)
            d = _np_dtype(aval.dtype) if hasattr(aval, "dtype") else None
            if d is not None and hasattr(aval, "shape"):
                out_sigs.add((tuple(aval.shape), d.name))
        hits = []
        for i, (v, name, role) in enumerate(
                zip(jaxpr.invars, g.arg_names, g.roles)):
            if role not in droppable \
                    or (i < len(g.donated) and g.donated[i]):
                continue
            aval = v.aval
            d = _np_dtype(aval.dtype) if hasattr(aval, "dtype") else None
            if d is None or not hasattr(aval, "shape"):
                continue
            nbytes = int(onp.prod(aval.shape, dtype=onp.int64)
                         * d.itemsize) if len(aval.shape) else d.itemsize
            sig = (tuple(aval.shape), d.name)
            if nbytes >= min_bytes and sig in out_sigs:
                hits.append((name, nbytes, sig))
        if g.kind == "train":
            # one aggregated finding: a real model has hundreds of params
            if hits:
                total = sum(n for _, n, _ in hits)
                names = ", ".join(n for n, _, _ in hits[:3])
                more = f" (+{len(hits) - 3} more)" if len(hits) > 3 else ""
                ctx.diag("MX704",
                         f"{len(hits)} step buffer(s) totalling "
                         f"{total >> 10} KiB ({names}{more}) are replaced "
                         "by same-aval outputs but not donated: the step "
                         "holds two copies of the model/optimizer state — "
                         "build the trainer with donation enabled",
                         g, op=names, severity="warning")
            continue
        for name, nbytes, sig in hits:
            if (g.entry, name) in seen:
                continue
            seen.add((g.entry, name))
            ctx.diag("MX704",
                     f"input '{name}' ({nbytes >> 10} KiB, "
                     f"{sig[1]}{list(sig[0])}) is dropped after the "
                     "call and an output has the same aval, but the "
                     "buffer is not donated: XLA must allocate a "
                     "second buffer per call — donate request buffers "
                     "(CompiledModel donate='auto'/True)", g, op=name,
                     severity="warning")


# ---------------------------------------------------------------------------
# MX705 — large constants baked into the graph
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_constants",
                   describe="large constant baked into the graph "
                            "(>1 MiB literal), MX705")
def hlo_constants(ctx: HloPassContext) -> None:
    limit = int(ctx.opt("const_limit_bytes", 1 << 20))
    for g in ctx.graphs:
        for i, c in enumerate(getattr(g.closed, "consts", []) or []):
            nbytes = getattr(c, "nbytes", None)
            if nbytes is None:
                try:
                    nbytes = onp.asarray(c).nbytes
                except Exception:
                    continue
            if nbytes > limit:
                shape = tuple(getattr(c, "shape", ()))
                dtype = getattr(c, "dtype", "?")
                ctx.diag("MX705",
                         f"constant #{i} ({nbytes / 2**20:.1f} MiB, "
                         f"{dtype}{list(shape)}) is baked into the "
                         "compiled graph: it is re-serialized into every "
                         "executable and bucket — pass it as an argument "
                         "(a parameter) instead of closing over it", g,
                         op=f"const#{i}", severity="error")


# ---------------------------------------------------------------------------
# MX706 — trace-signature divergence across call sites
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_signature",
                   describe="trace-signature divergence across call sites "
                            "(static twin of the compile ledger), MX706")
def hlo_signature(ctx: HloPassContext) -> None:
    by_entry: Dict[str, List[TracedGraph]] = {}
    for g in ctx.graphs:
        by_entry.setdefault(g.entry, []).append(g)
    for entry, graphs in by_entry.items():
        for g in graphs:
            if g.expected is False:
                ctx.diag("MX706",
                         "call-site signature is not in the declared "
                         "bucket/export set: this shape reaches the model "
                         "unbucketed and costs a fresh XLA compile (the "
                         "telemetry compile ledger will log it as a "
                         "post-warmup compile at runtime)", g,
                         severity="error")
        undeclared = [g for g in graphs if g.expected is None]
        sigs: Dict[tuple, List[str]] = {}
        for g in undeclared:
            sigs.setdefault(g.signature, []).append(g.site)
        if len(sigs) > 1:
            sites = "; ".join(
                f"{'+'.join(v)}→({_sig_str(k)})" for k, v in sigs.items())
            ctx.diag("MX706",
                     f"{len(sigs)} distinct lowered signatures across "
                     f"call sites of one model [{sites}]: each is a "
                     "separate XLA compile at runtime — route the call "
                     "sites through one bucketed entry "
                     "(serve.CompiledModel) or pad to a shared signature",
                     node=f"{entry}[{len(sigs)} sites]",
                     severity="warning")


# ---------------------------------------------------------------------------
# MX708 — mesh-configured trainer step: no per-parameter host work, full
#         donation (the compiled-collective contract of the pjit step)
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_mesh_step",
                   describe="mesh-configured trainer step contains a "
                            "per-parameter host round-trip or a "
                            "non-donated >=64KiB gradient/state buffer, "
                            "MX708")
def hlo_mesh_step(ctx: HloPassContext) -> None:
    """The hard contract behind ``ShardedTrainer``'s default pjit path:
    a training step traced on a real mesh (any axis > 1) must run as ONE
    compiled call — gradient exchange inside the graph as XLA collectives,
    parameter/optimizer buffers donated. A host callback or a live-data
    ``device_put`` in the step graph is the per-parameter push/pull loop
    sneaking back in (errors); so is a >=64KiB parameter/state input the
    step replaces-but-does-not-donate (two resident copies of the model,
    errors). The per-parameter loop is legal ONLY behind the named
    ``MXTPU_KVSTORE_FALLBACK=1`` opt-in — which never traces as a single
    step graph, so this pass cannot fire on it."""
    min_bytes = int(ctx.opt("donation_min_bytes", 1 << 16))
    for g in ctx.graphs:
        if g.kind != "train":
            continue
        axes = g.mesh_axes or {}
        if not axes or max(axes.values(), default=1) <= 1:
            continue                  # single-device "mesh": no contract
        mesh_s = ",".join(f"{k}={v}" for k, v in sorted(axes.items())
                          if v > 1)
        # forward reach from the ARGUMENT invars only (constvars are
        # trace-time constants XLA materializes once — same liveness rule
        # MX701 applies): a device_put is a per-step transfer only when
        # it moves argument-derived data
        hosty = []

        def scan(jaxpr, live):
            reach = set(live)
            for eqn in jaxpr.eqns:
                live_in = any(not _is_literal(v) and v in reach
                              for v in eqn.invars)
                if live_in:
                    reach.update(eqn.outvars)
                name = eqn.primitive.name
                if name in _CALLBACK_PRIMS or name.endswith("_callback"):
                    hosty.append(name)
                elif name in _TRANSFER_PRIMS and live_in:
                    hosty.append(name)
                for v in eqn.params.values():
                    for sub in _jaxprs_in(v):
                        scan(sub, set(sub.invars) if live_in else set())

        scan(g.closed.jaxpr, set(g.closed.jaxpr.invars))
        if hosty:
            uniq = sorted(set(hosty))
            ctx.diag("MX708",
                     f"mesh step ({mesh_s}) contains {len(hosty)} host "
                     f"round-trip op(s) ({', '.join(uniq[:4])}): every "
                     "executed step pays a device→host→device transfer "
                     "inside the compiled graph — gradient exchange must "
                     "lower to XLA collectives (the pjit step), with the "
                     "per-parameter loop only behind "
                     "MXTPU_KVSTORE_FALLBACK=1", g,
                     op=uniq[0], severity="error")
        if g.donated is None:
            continue
        jaxpr = g.closed.jaxpr
        out_sigs = set()
        for o in jaxpr.outvars:
            aval = getattr(o, "aval", None)
            d = _np_dtype(aval.dtype) if hasattr(aval, "dtype") else None
            if d is not None and hasattr(aval, "shape"):
                out_sigs.add((tuple(aval.shape), d.name))
        hits = []
        for i, (v, name, role) in enumerate(
                zip(jaxpr.invars, g.arg_names, g.roles)):
            if role not in ("param", "state") \
                    or (i < len(g.donated) and g.donated[i]):
                continue
            aval = v.aval
            d = _np_dtype(aval.dtype) if hasattr(aval, "dtype") else None
            if d is None or not hasattr(aval, "shape"):
                continue
            nbytes = int(onp.prod(aval.shape, dtype=onp.int64)
                         * d.itemsize) if len(aval.shape) else d.itemsize
            if nbytes >= min_bytes and (tuple(aval.shape), d.name) in out_sigs:
                hits.append((name, nbytes))
        if hits:
            total = sum(n for _, n in hits)
            names = ", ".join(n for n, _ in hits[:3])
            more = f" (+{len(hits) - 3} more)" if len(hits) > 3 else ""
            ctx.diag("MX708",
                     f"mesh step ({mesh_s}) holds {len(hits)} non-donated "
                     f">=64KiB parameter/optimizer buffer(s) totalling "
                     f"{total >> 10} KiB ({names}{more}) that same-aval "
                     "outputs replace: the step keeps two copies of the "
                     "sharded state resident — build the trainer with "
                     "donation enabled (donate=True, the default)", g,
                     op=names, severity="error")

"""MX71x — dtype-flow verification of quantized compiled graphs.

``quantization.quantize_net``/``quantize_model`` swap float layers for
int8 twins, but the property that matters — *the compute the TPU runs is
actually int8* — only exists in the compiled graph. Source-level checks
cannot see a ``jnp.matmul`` that silently promoted its int8 operand back
to f32, or a calibration range that lowered to an online ``reduce_max``
instead of a baked constant. This pass family walks the traced jaxpr
propagating a per-var compute-dtype lattice with quantize/dequantize
boundary detection (a quantize boundary is a ``convert_element_type`` to
int8; a dequantize boundary is an integer→float convert) and proves the
declared-int8 regions hold:

==========  =============================================================
``MX710``   informational quantized-region summary (boundaries, int8
            matmuls, bytes saved vs churned) — opt-in via ``quant=True``
``MX711``   silent f32 promotion inside a declared-int8 region: an int8
            tensor is widened back to float and feeds a float matmul
``MX712``   quantize boundary with no calibration provenance: the range
            is an online min/max reduction over the data itself
``MX713``   q/dq pairing hazard: re-quantization with no intervening
            compute (double quantization / scale-mismatch round trip)
``MX714``   additive reduction accumulating in int8 (must widen)
``MX715``   boundary churn: q/dq convert traffic exceeds the f32 bytes
            the int8 compute saves (priced via ``analysis.hlo.cost``)
==========  =============================================================

Detection runs over a *flattened* view of each graph: transparent call
primitives (``pjit`` — every ``jnp.clip``/``jnp.round`` helper lowers to
one — plus custom-derivative wrappers) are inlined with var
substitution, so dataflow walks cross them; control-flow bodies
(scan/while/cond) stay separate scopes, analyzed independently.

Every detection is a deterministic pure function of the jaxpr, so the
pass is safe at ``ModelRegistry`` staging time: an un-calibrated or
silently-promoted quantized version is rejected before its first device
step while the active version keeps serving. Float graphs have no
quantize boundaries and produce zero findings — the pass costs one jaxpr
walk on the f32 zoo and never fires there.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as onp

from .passes import _is_literal, _np_dtype, register_hlo_pass
from .trace import TracedGraph, _jaxprs_in

__all__ = ["quant_graph_stats", "QuantGraphStats"]

#: matmul-shaped compute — the eqns a declared-int8 region exists to feed
_MATMUL_PRIMS = frozenset({"dot_general", "conv_general_dilated"})

#: additive reductions whose accumulator width is the accuracy hazard
#: MX714 guards (max/min are order statistics — int8-safe)
_ACCUM_REDUCE_PRIMS = frozenset({
    "reduce_sum", "cumsum", "cumlogsumexp", "reduce_window_sum",
    "add_any", "reduce_prod", "cumprod",
})

#: the elementwise chain a quantize op lowers to between the f32 data and
#: the int8 convert (scale-divide, round, clamp) — followed backwards by
#: the MX712 provenance walk to separate the data path from the scale path
_Q_CHAIN_PRIMS = frozenset({
    "div", "mul", "add", "sub", "max", "min", "clamp", "round",
    "nextafter", "convert_element_type", "reshape", "broadcast_in_dim",
})

#: call-shaped primitives inlined by the flattener — one sub-jaxpr,
#: invars/outvars align one-to-one with the sub-jaxpr's
_TRANSPARENT_CALLS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "remat", "checkpoint",
    "remat_call", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
})


def _dt(v):
    aval = getattr(v, "aval", None)
    return _np_dtype(aval.dtype) if hasattr(aval, "dtype") else None


def _is_int8(d) -> bool:
    return d is not None and d.kind in ("i", "u") and d.itemsize == 1


def _is_int(d) -> bool:
    return d is not None and d.kind in ("i", "u")


def _is_float(d) -> bool:
    return d is not None and d.kind in ("f", "c")


def _nbytes_var(v) -> int:
    from .cost import _nbytes
    aval = getattr(v, "aval", None)
    return _nbytes(aval) if aval is not None else 0


def _shape_elems(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1
    return int(onp.prod(shape, dtype=onp.int64))


# ---------------------------------------------------------------------------
# flattened dataflow view
# ---------------------------------------------------------------------------

class _FlatEqn:
    """One equation of the flattened graph: call boundaries dissolved,
    invars substituted back to their producing scope's vars."""
    __slots__ = ("name", "invars", "outvars", "params")

    def __init__(self, name, invars, outvars, params):
        self.name = name
        self.invars = invars
        self.outvars = outvars
        self.params = params


class _PVar:
    """Per-inline-instance proxy for an equation output. jax caches and
    reuses sub-jaxpr objects (two ``jnp.clip`` calls share one jaxpr),
    so the original outvars are NOT unique across inline instances —
    every flattened equation gets fresh proxies carrying the aval."""
    __slots__ = ("aval",)

    def __init__(self, aval):
        self.aval = aval


def _resolve(v, env):
    while not _is_literal(v) and v in env:
        v = env[v]
    return v


def _flatten_into(jaxpr, env, out: List[_FlatEqn], scopes: List) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        subs = [s for val in eqn.params.values() for s in _jaxprs_in(val)]
        if name in _TRANSPARENT_CALLS and len(subs) == 1 \
                and len(subs[0].invars) == len(eqn.invars):
            sub = subs[0]
            for sv, ov in zip(sub.invars, eqn.invars):
                env[sv] = ov if _is_literal(ov) else _resolve(ov, env)
            _flatten_into(sub, env, out, scopes)
            for sv, ov in zip(sub.outvars, eqn.outvars):
                env[ov] = sv if _is_literal(sv) else _resolve(sv, env)
            continue
        ivs = [v if _is_literal(v) else _resolve(v, env)
               for v in eqn.invars]
        ovs = []
        for o in eqn.outvars:
            p = _PVar(getattr(o, "aval", None))
            env[o] = p
            ovs.append(p)
        out.append(_FlatEqn(name, ivs, ovs, eqn.params))
        scopes.extend(subs)     # opaque control-flow bodies: own scopes


def _flat_scopes(jaxpr):
    """Yield one flattened equation list per dataflow scope: the top
    level (with transparent calls inlined), then each control-flow body
    reachable from it, recursively. Vars never cross scopes."""
    pending = [jaxpr]
    while pending:
        j = pending.pop(0)
        out: List[_FlatEqn] = []
        _flatten_into(j, {}, out, pending)
        yield out


def _producer_map(eqns: List[_FlatEqn]) -> Dict:
    prod = {}
    for eqn in eqns:
        for o in eqn.outvars:
            prod[o] = eqn
    return prod


def _is_q_convert(eqn) -> bool:
    """A quantize boundary: convert_element_type float → int8, tensor
    shaped (scalar converts are range/bound arithmetic, not data)."""
    return (eqn.name == "convert_element_type"
            and _is_int8(_dt(eqn.outvars[0]))
            and _is_float(_dt(eqn.invars[0]))
            and _shape_elems(eqn.outvars[0]) > 1)


def _is_dq_convert(eqn) -> bool:
    """A dequantize boundary: convert_element_type integer → float,
    tensor shaped."""
    return (eqn.name == "convert_element_type"
            and _is_float(_dt(eqn.outvars[0]))
            and _is_int(_dt(eqn.invars[0]))
            and _shape_elems(eqn.outvars[0]) > 1)


def _int_matmul_operands(eqn) -> List:
    if eqn.name not in _MATMUL_PRIMS:
        return []
    ops = [v for v in eqn.invars[:2] if _is_int8(_dt(v))]
    return ops if ops else []


class QuantGraphStats:
    """Boundary census of one traced graph (every dataflow scope):
    quantize/dequantize converts, int8 matmuls, and the byte economics
    MX715 gates on — all via the same ``_nbytes`` element-width pricing
    ``analysis.hlo.cost`` uses, so the churn verdict and the banked
    proxy can never disagree."""

    def __init__(self):
        self.q_converts: List[_FlatEqn] = []
        self.dq_converts: List[_FlatEqn] = []
        self.int_matmuls: List[_FlatEqn] = []
        self.wasted_boundaries: List[_FlatEqn] = []  # not matmul-adjacent
        self.saved_bytes: int = 0
        self.churn_bytes: int = 0

    @property
    def quantized(self) -> bool:
        return bool(self.q_converts or self.int_matmuls)


def _scope_stats(eqns: List[_FlatEqn], prod, stats: QuantGraphStats):
    q_here, dq_here, mm_here = [], [], []
    for eqn in eqns:
        if _is_q_convert(eqn):
            q_here.append(eqn)
        elif _is_dq_convert(eqn):
            dq_here.append(eqn)
        ops = _int_matmul_operands(eqn)
        if ops:
            mm_here.append(eqn)
            stats.saved_bytes += 3 * sum(_nbytes_var(v) for v in ops)
    stats.q_converts += q_here
    stats.dq_converts += dq_here
    stats.int_matmuls += mm_here
    if not (q_here or dq_here):
        return
    # integer-typed dataflow closure around the int8 matmuls: backward
    # from their int8 operands, forward from their outputs — a boundary
    # convert outside that closure moves bytes for no int8 compute
    useful = set()
    back = [v for e in mm_here for v in _int_matmul_operands(e)]
    seen = set()
    while back:
        v = back.pop()
        if _is_literal(v) or id(v) in seen:
            continue
        seen.add(id(v))
        e = prod.get(v)
        if e is None:
            continue
        if _is_q_convert(e):
            useful.add(id(e))
            continue                              # float side: stop
        if all(_is_int(_dt(o)) for o in e.outvars):
            back.extend(iv for iv in e.invars if not _is_literal(iv))
    consumers: Dict = {}
    for eqn in eqns:
        for iv in eqn.invars:
            if not _is_literal(iv):
                consumers.setdefault(id(iv), []).append(eqn)
    fwd = [o for e in mm_here for o in e.outvars]
    seen_f = set()
    while fwd:
        v = fwd.pop()
        if id(v) in seen_f:
            continue
        seen_f.add(id(v))
        for e in consumers.get(id(v), ()):
            if _is_dq_convert(e):
                useful.add(id(e))
                continue                          # float side: stop
            if all(_is_int(_dt(o)) for o in e.outvars):
                fwd.extend(e.outvars)
    for eqn in q_here + dq_here:
        if id(eqn) in useful:
            continue
        stats.wasted_boundaries.append(eqn)
        stats.churn_bytes += (_nbytes_var(eqn.invars[0])
                              + _nbytes_var(eqn.outvars[0]))


def quant_graph_stats(g: TracedGraph) -> QuantGraphStats:
    """Census the quantization boundaries of one traced graph.

    ``saved_bytes``: 3× the int8 operand bytes of every int8 matmul/conv
    (the same operands at f32 would be 4× the width — weights and
    activations stream from HBM at a quarter the traffic).
    ``churn_bytes``: in+out bytes of every q/dq boundary convert NOT
    connected to an int8 matmul through an integer-typed dataflow chain —
    a quantize round trip that feeds no int8 compute moves bytes for
    nothing. A clean quantized layer (q → int8 dot → dq) contributes to
    ``saved_bytes`` only.
    """
    stats = QuantGraphStats()
    for eqns in _flat_scopes(g.closed.jaxpr):
        _scope_stats(eqns, _producer_map(eqns), stats)
    return stats


# ---------------------------------------------------------------------------
# per-detection walks (each over one flattened scope)
# ---------------------------------------------------------------------------

def _silent_promotions(eqns: List[_FlatEqn]) -> List[Tuple]:
    """MX711: int8 values widened back to float that reach a float-typed
    matmul/conv. Taint starts at int8→float converts, propagates through
    float-typed non-matmul eqns, and dies at any convert to a non-float
    dtype — so a bias re-encode (int8 → f32 → int32) or a legitimate
    dequantize→re-quantize between layers never taints the next layer's
    int8 dot."""
    tainted: set = set()
    hits = []
    for eqn in eqns:
        if eqn.name in _MATMUL_PRIMS:
            out_d = _dt(eqn.outvars[0])
            if _is_float(out_d) and any(
                    not _is_literal(v) and id(v) in tainted
                    for v in eqn.invars[:2]):
                hits.append((eqn, out_d))
            continue                 # matmul output is fresh, not tainted
        if (eqn.name == "convert_element_type"
                and _is_int8(_dt(eqn.invars[0]))
                and _is_float(_dt(eqn.outvars[0]))):
            tainted.add(id(eqn.outvars[0]))
            continue
        if any(not _is_literal(v) and id(v) in tainted
               for v in eqn.invars):
            for o in eqn.outvars:
                if _is_float(_dt(o)):
                    tainted.add(id(o))
    return hits


def _online_range_boundaries(eqns: List[_FlatEqn], prod) -> List:
    """MX712: quantize boundaries whose scale derives from a min/max
    reduction over the tensor being quantized (the ``quantize_v2`` online
    branch) instead of a baked calibrated constant. The walk follows the
    quantize lowering chain backwards from the int8 convert, splitting
    each step into the (non-scalar) data path and the (scalar) scale
    operands — seeing through the broadcast jnp inserts around a scalar
    scale — then closes over the scale operands' ancestry looking for a
    reduction over non-scalar input."""
    def _scalar_root(v, depth=4):
        if _is_literal(v):
            return None
        if _shape_elems(v) <= 1:
            return v
        e = prod.get(v)
        if (depth > 0 and e is not None and e.name in
                ("broadcast_in_dim", "reshape", "convert_element_type")):
            return _scalar_root(e.invars[0], depth - 1)
        return None

    hits = []
    for eqn in eqns:
        if not _is_q_convert(eqn):
            continue
        scale_roots: List = []
        frontier = [eqn.invars[0]]
        for _ in range(16):
            if not frontier:
                break
            v = frontier.pop()
            if _is_literal(v):
                continue
            e = prod.get(v)
            if e is None or e.name not in _Q_CHAIN_PRIMS:
                continue
            data = []
            for iv in e.invars:
                if _is_literal(iv):
                    continue
                root = _scalar_root(iv)
                if root is not None:
                    scale_roots.append(root)
                else:
                    data.append(iv)
            frontier += data[:1]
        walk = list(scale_roots)
        seen = set()
        online = False
        while walk and not online:
            v = walk.pop()
            if _is_literal(v) or id(v) in seen:
                continue
            seen.add(id(v))
            e = prod.get(v)
            if e is None:
                continue
            if (e.name in ("reduce_max", "reduce_min", "reduce_sum")
                    and any(_shape_elems(iv) > 1 for iv in e.invars
                            if not _is_literal(iv))):
                online = True
                break
            walk.extend(iv for iv in e.invars if not _is_literal(iv))
        if online:
            hits.append(eqn)
    return hits


def _requantize_pairs(eqns: List[_FlatEqn], prod) -> List:
    """MX713: a quantize boundary whose backward slice — followed through
    boundary converts and elementwise/movement glue but stopped at any
    matmul/conv/reduction (real compute) — contains another quantize
    boundary: the tensor went q→dq→q with nothing computed in between,
    i.e. double quantization / a redundant round trip whose two scales
    can silently disagree."""
    stop = _MATMUL_PRIMS | _ACCUM_REDUCE_PRIMS | frozenset(
        {"reduce_max", "reduce_min", "reduce_window_max",
         "reduce_window_min"})
    hits = []
    for eqn in eqns:
        if not _is_q_convert(eqn):
            continue
        seen = set()
        walk = [v for v in eqn.invars if not _is_literal(v)]
        found = None
        for _ in range(256):
            if not walk or found is not None:
                break
            v = walk.pop()
            if id(v) in seen:
                continue
            seen.add(id(v))
            e = prod.get(v)
            if e is None or e.name in stop:
                continue
            if _is_q_convert(e):
                found = e
                continue
            walk.extend(iv for iv in e.invars if not _is_literal(iv))
        if found is not None:
            hits.append((eqn, found))
    return hits


def _narrow_accumulations(eqns: List[_FlatEqn]) -> List:
    """MX714: additive reductions whose accumulator dtype is int8."""
    return [eqn for eqn in eqns
            if eqn.name in _ACCUM_REDUCE_PRIMS
            and _is_int8(_dt(eqn.outvars[0]))]


# ---------------------------------------------------------------------------
# the registered pass
# ---------------------------------------------------------------------------

@register_hlo_pass("hlo_quant",
                   describe="dtype-flow precision propagation over "
                            "quantized graphs: silent f32 promotion, "
                            "calibration provenance, q/dq pairing, int8 "
                            "accumulation, boundary churn, MX710-MX715")
def hlo_quant(ctx) -> None:
    """The MX71x family. Auto-detecting: a graph with no quantize
    boundary and no int8 matmul is skipped after one census walk, so the
    f32 zoo and every existing caller see zero findings at default
    options. ``quant=True`` (``verify(..., quant=True)``, the
    ``ModelRegistry`` staging gate) additionally emits the MX710
    informational region summary for quantized graphs."""
    emit_summary = bool(ctx.opt("quant", False))
    for g in ctx.graphs:
        scopes = list(_flat_scopes(g.closed.jaxpr))
        stats = QuantGraphStats()
        prods = [_producer_map(eqns) for eqns in scopes]
        for eqns, prod in zip(scopes, prods):
            _scope_stats(eqns, prod, stats)
        if not stats.quantized:
            continue
        n711 = n712 = n713 = 0
        for eqns, prod in zip(scopes, prods):
            for eqn, out_d in _silent_promotions(eqns)[:3]:
                n711 += 1
                ctx.diag(
                    "MX711",
                    f"'{eqn.name}' runs at {out_d.name} on an operand "
                    "that was quantized to int8 and silently widened "
                    "back to float: the matmul the int8 region exists "
                    "to feed left the MXU int8 path — keep the operand "
                    "int8 into the dot (preferred_element_type=int32) "
                    "and dequantize the accumulator instead", g,
                    op=eqn.name, severity="error")
            for eqn in _online_range_boundaries(eqns, prod)[:3]:
                n712 += 1
                ctx.diag(
                    "MX712",
                    "quantize boundary computes its range online "
                    "(min/max reduction over the data being quantized): "
                    "no calibration provenance backs the scale — every "
                    "step re-derives a different range and an outlier "
                    "batch silently reshapes the encoding; lower a "
                    "calibrated Observer range instead "
                    "(quantization.quantize_model)", g,
                    op=eqn.name, severity="error")
            for eqn, _prev in _requantize_pairs(eqns, prod)[:3]:
                n713 += 1
                ctx.diag(
                    "MX713",
                    "tensor is quantized twice with no intervening "
                    "compute (quantize → dequantize → quantize): the two "
                    "boundaries' scales can silently disagree and each "
                    "round trip loses precision — quantize once and keep "
                    "the int8 value", g, op=eqn.name, severity="error")
            for eqn in _narrow_accumulations(eqns)[:3]:
                ctx.diag(
                    "MX714",
                    f"'{eqn.name}' accumulates in int8: an 8-bit "
                    "accumulator overflows after ~2 terms at full scale "
                    "— softmax/normalization/mean reductions over "
                    "quantized values must widen to int32 or float "
                    "before reducing", g, op=eqn.name, severity="warning")
        if stats.churn_bytes > stats.saved_bytes:
            ctx.diag(
                "MX715",
                f"quantization boundary churn: {stats.churn_bytes} bytes "
                f"of q/dq convert traffic not adjacent to any int8 "
                f"matmul vs {stats.saved_bytes} bytes saved by "
                f"{len(stats.int_matmuls)} int8 matmul(s) — the "
                "quantized build moves more bytes than it saves "
                "(an anti-optimization): drop the unused boundaries or "
                "quantize the compute they were meant to feed", g,
                severity="warning")
        if emit_summary:
            ctx.diag(
                "MX710",
                f"quantized region summary: {len(stats.q_converts)} "
                f"quantize boundary(ies), {len(stats.dq_converts)} "
                f"dequantize boundary(ies), {len(stats.int_matmuls)} "
                f"int8 matmul(s); ~{stats.saved_bytes} bytes/step saved "
                f"vs {stats.churn_bytes} bytes boundary churn"
                + (f"; {n711 + n712 + n713} precision-flow error(s)"
                   if n711 + n712 + n713 else ""), g, severity="info")

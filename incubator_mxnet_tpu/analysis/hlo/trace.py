"""Entry-point tracing: any model entry → jaxpr + lowered StableHLO.

The compiled graph — not the Python source — determines what the TPU
actually executes (fusion, transfers, donation, baked constants), so the
MX7xx passes inspect :class:`TracedGraph` records produced here rather
than ASTs. One tracer per entry-point family:

- a live :class:`~incubator_mxnet_tpu.gluon.block.HybridBlock` (traced
  through the same inference pure function ``export()`` serializes);
- a :class:`~incubator_mxnet_tpu.serve.CompiledModel` (one graph per
  bucket assignment, donation intent included);
- a cold-loaded :class:`~incubator_mxnet_tpu.gluon.block.SymbolBlock`
  artifact (per baked signature, via ``jax.export`` round-trip);
- a :class:`~incubator_mxnet_tpu.parallel.ShardedTrainer` step (the full
  fwd+bwd+optimizer jaxpr, donation flags read off the jitted entry);
- any plain callable + sample args.

Tracing never triggers an XLA *compile* — ``jax.make_jaxpr`` only runs
the Python trace, and the StableHLO text is lowered lazily on demand —
so the passes are safe to run at serve staging time and in CI. One
exception, same contract as ``CompiledModel(example_args=...)``: a
HybridBlock that has never recorded a forward is hybridized and given
ONE eager warmup call with the first ``sample_args`` site (finishing
deferred parameter init and recording the call signature; the first call
of a fresh hybridized block runs eagerly, outside the jit cache).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ...base import MXNetError
from ..diagnostics import Diagnostic, Report

__all__ = ["TracedGraph", "TraceResult", "trace_entry", "walk_eqns"]


@dataclass
class TracedGraph:
    """One lowered call site: the (unwrapped) closed jaxpr plus the
    calling-convention metadata the MX7xx passes need.

    ``arg_names``/``roles`` align with ``closed.jaxpr.invars``; roles are
    ``"rng_key" | "input" | "param" | "state" | "other"``. ``donated`` is
    per-invar donation intent (``None`` = donation not applicable/unknown,
    e.g. a bare block — the donation pass skips those). ``signature`` is
    the (shape, dtype) tuple of the ``input``-role invars — the static
    twin of the telemetry compile-ledger key. ``expected`` records whether
    this signature was declared up front (a bucket assignment / exported
    signature); ``False`` means an unbucketed call site reached the model
    and is reported as an error-severity MX706. The in-tree compiled
    tracer diagnoses its own overflow samples directly, so ``False`` is
    primarily the contract for custom tracers that hand-build
    TracedGraphs for :func:`~..passes.run_hlo_passes`.
    """

    entry: str
    site: str
    closed: Any                      # jax ClosedJaxpr
    arg_names: List[str]
    roles: List[str]
    kind: str = "infer"              # "infer" | "train"
    donated: Optional[Tuple[bool, ...]] = None
    signature: tuple = ()
    expected: Optional[bool] = None
    #: named mesh axis sizes the graph was traced under (``None`` = no
    #: mesh context) — lets the cost model price collectives
    mesh_axes: Optional[Dict[str, int]] = None
    #: per-invar PartitionSpec (``None`` entries = unknown/replicated),
    #: aligned with ``closed.jaxpr.invars`` — the SPMD resource contract
    #: the cost model derives implied gradient-exchange collectives from
    in_specs: Optional[List] = None
    _lower: Optional[Callable[[], str]] = None

    def hlo_text(self) -> str:
        """Lowered StableHLO text (lazy — only the first call pays the
        lowering; the text is memoized)."""
        if self._lower is None:
            raise MXNetError(f"{self.entry}[{self.site}] was built without "
                             "a lowering hook; construct the TracedGraph "
                             "with _lower=<zero-arg callable returning the "
                             "StableHLO text> to make hlo_text() available")
        if getattr(self, "_hlo_cache", None) is None:
            self._hlo_cache = self._lower()
        return self._hlo_cache

    @property
    def label(self) -> str:
        return f"{self.entry}[{self.site}]"


@dataclass
class TraceResult:
    graphs: List[TracedGraph] = field(default_factory=list)
    #: notes about coverage limits (surfaced via Report.skipped)
    skipped: List[str] = field(default_factory=list)
    #: diagnostics raised by tracing itself (e.g. bucket overflow)
    diags: List[Diagnostic] = field(default_factory=list)


def walk_eqns(jaxpr):
    """Yield every eqn in a (open) jaxpr, recursing into sub-jaxprs held
    in eqn params (pjit / scan / cond bodies) — duck-typed so it works
    across jax versions."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _jaxprs_in(v):
                yield from walk_eqns(sub)


def _jaxprs_in(v):
    """Open jaxprs held in an eqn-param value. ClosedJaxpr is checked
    FIRST: it also exposes ``.eqns`` (delegated), but only the open
    ``.jaxpr`` carries ``.invars``."""
    if hasattr(v, "jaxpr") and hasattr(v, "consts"):     # ClosedJaxpr
        yield v.jaxpr
    elif hasattr(v, "eqns"):                    # open Jaxpr
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _jaxprs_in(x)


def _unwrap_pjit(closed):
    """make_jaxpr over a jitted callable yields one wrapping pjit eqn;
    return (inner ClosedJaxpr, donated_invars) when that shape holds,
    else (closed, None)."""
    jaxpr = closed.jaxpr
    if len(jaxpr.eqns) == 1 and jaxpr.eqns[0].primitive.name == "pjit":
        eqn = jaxpr.eqns[0]
        inner = eqn.params.get("jaxpr")
        donated = eqn.params.get("donated_invars")
        if inner is not None and hasattr(inner, "jaxpr") \
                and len(inner.jaxpr.invars) == len(jaxpr.invars):
            return inner, (tuple(donated) if donated is not None else None)
    return closed, None


def _aval_of(a) -> Tuple[tuple, str]:
    from ...ndarray import NDArray
    if isinstance(a, NDArray):
        return tuple(a.shape), str(a._data.dtype)
    arr = onp.asarray(a) if not hasattr(a, "dtype") else a
    return tuple(getattr(arr, "shape", ())), str(arr.dtype)


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def _sites_of(sample_args):
    """Normalize the ``sample_args`` argument: one tuple of arrays = one
    call site; a list of tuples = several call sites."""
    if sample_args is None:
        return []
    if isinstance(sample_args, list):
        return [tuple(s) if isinstance(s, (list, tuple)) else (s,)
                for s in sample_args]
    if isinstance(sample_args, tuple):
        return [sample_args]
    return [(sample_args,)]


def _sig_str(sig) -> str:
    return ",".join(f"{'x'.join(map(str, s))}:{d}" for s, d in sig)


# ---------------------------------------------------------------------------
# per-entry tracers
# ---------------------------------------------------------------------------

def _trace_block(block, sample_args, max_graphs: int) -> TraceResult:
    """Trace a live HybridBlock through the same inference-mode pure
    function ``export()``/``CompiledModel`` use. Each sample-args set is
    one call site; the recorded ``_last_sig`` is the fallback site."""
    import jax

    from ... import random as random_mod

    res = TraceResult()
    sites = _sites_of(sample_args)
    if getattr(block, "_last_sig", None) is None:
        if not sites:
            raise MXNetError(
                "analysis.hlo needs a traced graph: call hybridize() and "
                "run one forward, or pass sample_args")
        if not block._active:
            block.hybridize()
        block(*sites[0])      # establish skeleton / parameter set
    skeleton, n_in, rec_avals, ctx = block._last_sig
    params = getattr(block, "_cached_params", [])
    name_by_id = {id(p): k for k, p in
                  block._collect_params_with_prefix().items()}
    pnames = [name_by_id.get(id(p), f"param:{i}")
              for i, p in enumerate(params)]
    impl = random_mod._impl()
    key_data = jax.random.key_data(jax.random.key(0, impl=impl))
    entry = type(block).__name__

    site_sigs = []
    for i, site in enumerate(sites):
        arrs = [a for a in site]
        if len(arrs) != n_in:
            raise MXNetError(f"sample_args[{i}] has {len(arrs)} arrays but "
                             f"the model takes {n_in}")
        site_sigs.append(("site%d" % i, [_aval_of(a) for a in arrs]))
    if not site_sigs:
        site_sigs = [("recorded", [(tuple(s), str(d)) for s, d in rec_avals])]
    if len(site_sigs) > max_graphs:
        res.skipped.append(
            f"hlo: traced {max_graphs}/{len(site_sigs)} call sites of "
            f"{entry}")
        site_sigs = site_sigs[:max_graphs]

    for site, sig in site_sigs:
        pure, _meta = block._make_pure_infer(skeleton, n_in, ctx)
        avals = [_sds(key_data.shape, key_data.dtype)]
        avals += [_sds(s, d) for s, d in sig]
        avals += [_sds(tuple(p.shape), p.dtype) for p in params]
        closed = jax.make_jaxpr(pure)(*avals)
        closed, donated = _unwrap_pjit(closed)
        res.graphs.append(TracedGraph(
            entry=entry, site=site, closed=closed,
            arg_names=(["rng_key"] + [f"input:{i}" for i in range(n_in)]
                       + pnames),
            roles=(["rng_key"] + ["input"] * n_in + ["param"] * len(params)),
            donated=donated,
            signature=tuple((tuple(s), str(d)) for s, d in sig),
            # lazy lowering hook, invoked at most once per graph
            _lower=(lambda p=pure, av=tuple(avals):
                    jax.jit(p).lower(*av).as_text())))  # mxlint: disable=MX501
    return res


def _trace_compiled(cm, sample_args, max_graphs: int) -> TraceResult:
    """One graph per bucket assignment of a CompiledModel (all marked
    ``expected``), plus one per sample-args call site checked against the
    bucket table — a sample that overflows the table is the unbucketed-
    shape bug, reported as an MX706 diagnostic right here."""
    import jax

    from ...serve.buckets import BucketOverflow

    res = TraceResult()
    entry = type(cm._block).__name__
    n_in = cm._n_in
    if cm._mode == "artifact":
        fns = None
        donated = None
    else:
        fns = cm._pure
        req = getattr(cm, "_donate_requested", "auto")
        donated = None if req is None else (
            (False,) + (req in ("auto", True),) * n_in
            + (False,) * len(cm._pvals))

    assignments = list(cm._table.assignments())
    # EVERY bucket signature is "declared" even when tracing is capped —
    # a sample landing in an untraced-but-declared bucket must not be
    # reported as unbucketed (MX706)
    declared = {tuple(cm.signature_for(a)) for a in assignments}
    if len(assignments) > max_graphs:
        res.skipped.append(
            f"hlo: traced {max_graphs}/{len(assignments)} bucket "
            f"signatures of {entry}")
        assignments = assignments[:max_graphs]

    def one(site, sig, expected):
        avals = [_sds(cm._key_data.shape, cm._key_data.dtype)]
        avals += [_sds(s, d) for s, d in sig]
        avals += [_sds(p.shape, p.dtype) for p in cm._pvals]
        if cm._mode == "artifact":
            ins = [_sds(s, d) for s, d in sig]
            fn = cm._block._sig_for(ins)["exported"].call
        else:
            fn = fns
        closed = jax.make_jaxpr(fn)(*avals)
        closed, unwrapped_donated = _unwrap_pjit(closed)
        res.graphs.append(TracedGraph(
            entry=entry, site=site, closed=closed,
            arg_names=(["rng_key"] + [f"input:{i}" for i in range(n_in)]
                       + [f"param:{i}" for i in range(len(cm._pvals))]),
            roles=(["rng_key"] + ["input"] * n_in
                   + ["param"] * len(cm._pvals)),
            donated=donated if donated is not None else unwrapped_donated,
            signature=tuple((tuple(s), str(d)) for s, d in sig),
            expected=expected,
            # lazy lowering hook, invoked at most once per graph
            _lower=(lambda f=fn, av=tuple(avals):
                    jax.jit(f).lower(*av).as_text())))  # mxlint: disable=MX501

    seen = set()
    for assignment in assignments:
        sig = cm.signature_for(assignment)
        key = tuple(sig)
        if key in seen:
            continue
        seen.add(key)
        site = ",".join(f"{k}={v}" for k, v in sorted(assignment.items()))
        one(site, sig, expected=True)

    for i, sample in enumerate(_sites_of(sample_args)):
        arrays = [onp.asarray(a) if not hasattr(a, "shape") else a
                  for a in sample]
        try:
            sizes = cm._sizes_of([onp.asarray(getattr(a, "_data", a))
                                  for a in arrays])
            assignment = cm._table.assignment(sizes)
        except BucketOverflow as e:
            res.diags.append(Diagnostic(
                "MX706", f"call site sample[{i}] does not fit the bucket "
                f"table ({e}) — this request shape reaches the model "
                "unbucketed and costs a fresh XLA compile per novel shape",
                node=f"{entry}[sample{i}]", pass_name="hlo_signature",
                severity="error"))
            continue
        sig = cm.signature_for(assignment)
        if tuple(sig) not in seen:
            seen.add(tuple(sig))
            one(f"sample{i}", sig, expected=tuple(sig) in declared)
    return res


def _trace_artifact(block, sample_args, max_graphs: int) -> TraceResult:
    """Every signature baked into an exported SymbolBlock artifact."""
    import jax

    res = TraceResult()
    entry = block._arch.get("block", "SymbolBlock") if block._arch \
        else "SymbolBlock"
    sigs = block._sigs
    if len(sigs) > max_graphs:
        res.skipped.append(f"hlo: traced {max_graphs}/{len(sigs)} artifact "
                           f"signatures of {entry}")
        sigs = sigs[:max_graphs]
    arch = block._arch
    order = list(arch.get("param_order", []))
    key = arch["key"]
    for i, ent in enumerate(sigs):
        sig = [(tuple(s), d) for s, d in ent["in_avals"]]
        fn = ent["exported"].call
        avals = [_sds(tuple(key["shape"]), key["dtype"])]
        avals += [_sds(s, d) for s, d in sig]
        avals += [_sds(tuple(block._param_arrays[n].shape),
                       block._param_arrays[n]._data.dtype) for n in order]
        closed = jax.make_jaxpr(fn)(*avals)
        closed, _don = _unwrap_pjit(closed)
        res.graphs.append(TracedGraph(
            entry=entry, site=f"sig{i}:{_sig_str(sig)}", closed=closed,
            arg_names=(["rng_key"]
                       + [f"input:{j}" for j in range(len(sig))] + order),
            roles=(["rng_key"] + ["input"] * len(sig)
                   + ["param"] * len(order)),
            donated=None,
            signature=tuple(sig), expected=True,
            # lazy lowering hook, invoked at most once per graph
            _lower=(lambda f=fn, av=tuple(avals):
                    jax.jit(f).lower(*av).as_text())))  # mxlint: disable=MX501
    return res


def _trace_trainer(trainer, sample_args) -> TraceResult:
    """The full sharded training step (fwd + bwd + optimizer + collectives)
    — the graph the telemetry compile ledger sees at ``trainer.step``."""
    import jax

    from ...parallel.mesh import active_mesh

    res = TraceResult()
    sites = _sites_of(sample_args)
    if not sites:
        raise MXNetError("analysis.hlo over a ShardedTrainer needs "
                         "sample_args=(one training batch)")
    args = trainer.step_trace_args(*sites[0])
    param_vals, opt_states, key, lr, t = args[:5]
    batch_vals = args[5:]
    names, roles, specs = [], [], []
    pnames = [p.name for p in trainer._params]
    param_shardings = list(trainer._param_shardings or [])
    state_shardings = [sh for tup in (trainer._state_shardings or [])
                       for sh in tup]
    for i, _ in enumerate(jax.tree_util.tree_leaves(tuple(param_vals))):
        names.append(pnames[i] if i < len(pnames) else f"param:{i}")
        roles.append("param")
        specs.append(param_shardings[i].spec
                     if i < len(param_shardings) else None)
    for i, _ in enumerate(jax.tree_util.tree_leaves(tuple(opt_states))):
        names.append(f"opt:{i}")
        roles.append("state")
        specs.append(state_shardings[i].spec
                     if i < len(state_shardings) else None)
    for n, r in [("rng_key", "rng_key"), ("lr", "other"), ("t", "other")]:
        names.append(n)
        roles.append(r)
        specs.append(None)
    for i, v in enumerate(batch_vals):
        names.append(f"input:{i}")
        roles.append("input")
        specs.append(getattr(getattr(v, "sharding", None), "spec", None))
    with active_mesh(trainer._mesh):
        closed = jax.make_jaxpr(trainer._step_fn)(*args)
    closed, donated = _unwrap_pjit(closed)
    if len(names) != len(closed.jaxpr.invars):
        # flattening mismatch (exotic optimizer state): degrade gracefully
        names = [f"arg:{i}" for i in range(len(closed.jaxpr.invars))]
        roles = ["other"] * len(names)
        specs = None
    res.graphs.append(TracedGraph(
        entry=type(trainer._block).__name__ + ".step", site="step",
        closed=closed, arg_names=names, roles=roles, kind="train",
        donated=donated,
        signature=tuple(_aval_of(v) for v in batch_vals),
        mesh_axes=dict(trainer._mesh.shape),
        in_specs=specs,
        _lower=(lambda fn=trainer._step_fn, av=args, m=trainer._mesh:
                _lower_in_mesh(fn, av, m))))
    return res


def _lower_in_mesh(fn, args, mesh):
    from ...parallel.mesh import active_mesh
    with active_mesh(mesh):
        return fn.lower(*args).as_text()


def _trace_callable(fn, sample_args, entry=None) -> TraceResult:
    import jax

    from ...parallel.mesh import current_active_mesh

    res = TraceResult()
    sites = _sites_of(sample_args)
    if not sites:
        raise MXNetError("analysis.hlo over a plain callable needs "
                         "sample_args")
    name = entry or getattr(fn, "__name__", type(fn).__name__)
    # tracing inside `with active_mesh(mesh):` gives the cost model the
    # axis sizes it needs to price explicit (shard_map) collectives
    mesh = current_active_mesh()
    mesh_axes = dict(mesh.shape) if mesh is not None else None
    for i, site in enumerate(sites):
        avals = [_sds(*_aval_of(a)) for a in site]
        closed = jax.make_jaxpr(fn)(*avals)
        closed, donated = _unwrap_pjit(closed)
        n = len(closed.jaxpr.invars)
        res.graphs.append(TracedGraph(
            entry=name, site=f"site{i}", closed=closed,
            arg_names=[f"input:{j}" for j in range(n)],
            roles=["input"] * n, donated=donated,
            signature=tuple(_aval_of(a) for a in site),
            mesh_axes=mesh_axes,
            # lazy lowering hook, invoked at most once per graph
            _lower=(lambda f=fn, av=tuple(avals):
                    jax.jit(f).lower(*av).as_text())))  # mxlint: disable=MX501
    return res


def trace_entry(model, sample_args=None, max_graphs: int = 8) -> TraceResult:
    """Dispatch one model entry point to its tracer. Accepts a
    CompiledModel, ShardedTrainer, SymbolBlock artifact, HybridBlock, or
    plain callable (+ ``sample_args``)."""
    from ...gluon.block import HybridBlock, SymbolBlock
    from ...serve.compiled import CompiledModel
    from ...serve.decode.engine import DecodeEngine
    try:
        from ...parallel.trainer import ShardedTrainer
    except Exception:                                    # pragma: no cover
        ShardedTrainer = ()
    if isinstance(model, DecodeEngine):
        # both graph families: every prefill bucket + the capacity-sized
        # decode step (the engine owns the assembly)
        return model.trace(max_graphs=max_graphs)
    if isinstance(model, CompiledModel):
        return _trace_compiled(model, sample_args, max_graphs)
    if ShardedTrainer and isinstance(model, ShardedTrainer):
        return _trace_trainer(model, sample_args)
    if isinstance(model, SymbolBlock):
        return _trace_artifact(model, sample_args, max_graphs)
    if isinstance(model, HybridBlock):
        return _trace_block(model, sample_args, max_graphs)
    if callable(model):
        return _trace_callable(model, sample_args)
    raise MXNetError(
        f"analysis.hlo cannot trace {type(model).__name__}; pass a "
        "HybridBlock, CompiledModel, SymbolBlock, ShardedTrainer, or a "
        "callable with sample_args")

"""``mx.analysis.hlo`` — compiled-graph inspection passes (MX7xx).

mxlint (MX2xx–MX6xx) sees Python ASTs; the telemetry compile ledger sees
recompiles only after they burn device wall-time. This layer closes the
gap: it traces any model entry point to the artifact the TPU actually
runs — a jaxpr plus (lazily) lowered StableHLO — and inspects it *before
the first device step*. Entry points: a live ``HybridBlock``, a
``serve.CompiledModel`` (per bucket), a ``SymbolBlock`` export artifact
(per baked signature), a ``parallel.ShardedTrainer`` step, or any plain
callable with sample args.

Programmatic entry point (called by ``serve.ModelRegistry.load`` and
``benchmark/serve_bench.py`` at staging time)::

    report = mx.analysis.hlo.verify(model, sample_args)
    report.raise_if_errors()

CLI::

    python -m tools.mxlint --hlo all --format=json
    python -m tools.mxlint --hlo bert_encoder
    python -m tools.mxlint --hlo my_pkg.my_mod:factory

Pass registry (the compiled-graph sibling of ``analysis/passes.py``):
``HLO_PASSES``, extendable with :func:`register_hlo_pass`.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..diagnostics import Report
from .passes import (  # noqa: F401
    HLO_PASSES, HloPassContext, list_hlo_passes, register_hlo_pass,
    run_hlo_passes,
)
from .trace import (  # noqa: F401
    TracedGraph, TraceResult, trace_entry, walk_eqns,
)

__all__ = ["verify", "trace_entry", "TracedGraph", "TraceResult",
           "HLO_PASSES", "register_hlo_pass", "list_hlo_passes",
           "run_hlo_passes", "walk_eqns"]


def verify(model, sample_args=None, *,
           passes: Optional[Sequence[str]] = None,
           max_graphs: int = 8,
           const_limit_bytes: int = 1 << 20,
           donation_min_bytes: int = 1 << 16) -> Report:
    """Trace ``model`` (every bucket/signature/call site, capped at
    ``max_graphs``) and run the registered MX7xx passes; returns the
    merged :class:`~..diagnostics.Report`.

    ``sample_args``: one tuple of arrays (one call site) or a list of
    tuples (several call sites — MX706 compares their lowered
    signatures). Optional for entries that carry their own signatures
    (a hybridized block with a recorded forward, a CompiledModel's
    bucket table, an export artifact). A block that has never run a
    forward is warmed with one eager call on the first sample site —
    the same signature-establishing contract as
    ``CompiledModel(example_args=...)`` — which mutates the block
    (hybridize + deferred parameter init).
    """
    result = trace_entry(model, sample_args, max_graphs=max_graphs)
    report = run_hlo_passes(result.graphs, names=passes,
                            const_limit_bytes=const_limit_bytes,
                            donation_min_bytes=donation_min_bytes)
    for d in result.diags:
        report.add(d)
    report.skipped.extend(result.skipped)
    return report

"""``mx.analysis.hlo`` — compiled-graph inspection passes (MX7xx).

mxlint (MX2xx–MX6xx) sees Python ASTs; the telemetry compile ledger sees
recompiles only after they burn device wall-time. This layer closes the
gap: it traces any model entry point to the artifact the TPU actually
runs — a jaxpr plus (lazily) lowered StableHLO — and inspects it *before
the first device step*. Entry points: a live ``HybridBlock``, a
``serve.CompiledModel`` (per bucket), a ``SymbolBlock`` export artifact
(per baked signature), a ``parallel.ShardedTrainer`` step, or any plain
callable with sample args.

Programmatic entry point (called by ``serve.ModelRegistry.load`` and
``benchmark/serve_bench.py`` at staging time)::

    report = mx.analysis.hlo.verify(model, sample_args)
    report.raise_if_errors()

The same traced graphs feed the device-blind cost model
(:mod:`~.cost`): ``mx.analysis.hlo.cost(model, sample_args)`` prices
FLOPs / bytes / transcendentals / fusion groups per graph — the numbers
``bench.py --proxy`` banks in ``PERF_PROXY.json`` and the CI
``perf-proxy`` job gates with a ±5% tolerance. ``verify(...,
cost=True)`` surfaces the table as informational MX707 diagnostics.

CLI::

    python -m tools.mxlint --hlo all --format=json
    python -m tools.mxlint --hlo bert_encoder
    python -m tools.mxlint --hlo my_pkg.my_mod:factory
    python -m tools.mxlint --hlo bert --cost

Pass registry (the compiled-graph sibling of ``analysis/passes.py``):
``HLO_PASSES``, extendable with :func:`register_hlo_pass`.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..diagnostics import Report
from .passes import (  # noqa: F401
    HLO_PASSES, HloPassContext, list_hlo_passes, register_hlo_pass,
    run_hlo_passes,
)
from .trace import (  # noqa: F401
    TracedGraph, TraceResult, trace_entry, walk_eqns,
)
from .cost import (  # noqa: F401  (importing registers hlo_cost/hlo_memory)
    CostReport, GraphCost, cost, cost_table, graph_cost, hbm_budget_bytes,
    ladder_peak_bytes, peak_live_bytes,
)
from .quant import (  # noqa: F401  (importing registers hlo_quant)
    QuantGraphStats, quant_graph_stats,
)

__all__ = ["verify", "verify_trace", "trace_entry", "TracedGraph",
           "TraceResult", "HLO_PASSES", "register_hlo_pass",
           "list_hlo_passes", "run_hlo_passes", "walk_eqns",
           "cost", "cost_table", "graph_cost", "CostReport", "GraphCost",
           "peak_live_bytes", "ladder_peak_bytes", "hbm_budget_bytes",
           "quant_graph_stats", "QuantGraphStats"]


def verify_trace(result: TraceResult, *,
                 passes: Optional[Sequence[str]] = None,
                 const_limit_bytes: int = 1 << 20,
                 donation_min_bytes: int = 1 << 16,
                 hbm_budget_bytes: Optional[int] = None,
                 cost: bool = False,
                 quant: bool = False) -> Report:
    """Run the MX7xx passes over an already-traced entry and fold in the
    tracer's own diagnostics/coverage notes — the shared second half of
    :func:`verify`, exposed so a caller that needs the
    :class:`~.trace.TraceResult` for something else (``mxlint --cost``
    prices the same graphs) traces exactly once."""
    report = run_hlo_passes(result.graphs, names=passes,
                            const_limit_bytes=const_limit_bytes,
                            donation_min_bytes=donation_min_bytes,
                            hbm_budget_bytes=hbm_budget_bytes,
                            cost=cost, quant=quant)
    for d in result.diags:
        report.add(d)
    report.skipped.extend(result.skipped)
    return report


def verify(model, sample_args=None, *,
           passes: Optional[Sequence[str]] = None,
           max_graphs: int = 8,
           const_limit_bytes: int = 1 << 20,
           donation_min_bytes: int = 1 << 16,
           hbm_budget_bytes: Optional[int] = None,
           cost: bool = False,
           quant: bool = False) -> Report:
    """Trace ``model`` (every bucket/signature/call site, capped at
    ``max_graphs``) and run the registered MX7xx passes; returns the
    merged :class:`~..diagnostics.Report`.

    ``sample_args``: one tuple of arrays (one call site) or a list of
    tuples (several call sites — MX706 compares their lowered
    signatures). Optional for entries that carry their own signatures
    (a hybridized block with a recorded forward, a CompiledModel's
    bucket table, an export artifact). A block that has never run a
    forward is warmed with one eager call on the first sample site —
    the same signature-establishing contract as
    ``CompiledModel(example_args=...)`` — which mutates the block
    (hybridize + deferred parameter init).

    ``cost=True`` additionally runs the informational ``hlo_cost`` pass,
    appending one MX707 info row per graph (the
    :func:`~.cost.graph_cost` table in diagnostic form).

    ``hbm_budget_bytes`` overrides the ``MXTPU_HBM_BUDGET`` env read of
    the MX709 memory pass (``None`` = read the env; unset env = the
    pass is silent).

    ``quant=True`` additionally emits the MX710 informational
    quantized-region summary per quantized graph. The MX711–MX715
    precision-flow checks themselves are always on — they fire only on
    graphs that actually contain quantize boundaries or int8 matmuls, so
    float models are unaffected. ``serve.ModelRegistry`` stages every
    version with ``quant=True``: an un-calibrated or silently-promoted
    int8 build is rejected before its first device step while the active
    version keeps serving.
    """
    return verify_trace(trace_entry(model, sample_args,
                                    max_graphs=max_graphs),
                        passes=passes, const_limit_bytes=const_limit_bytes,
                        donation_min_bytes=donation_min_bytes,
                        hbm_budget_bytes=hbm_budget_bytes, cost=cost,
                        quant=quant)

"""Analytic cost model over traced compiled graphs — the device-blind
perf proxy.

The device bench can go blind (a wedged TPU tunnel, no hardware in CI),
but the *compiled graph* is always available: ``trace.py`` lowers any
entry point to a jaxpr without an XLA compile. This module walks that
jaxpr and prices it — FLOPs (dot/conv from dimension numbers, everything
else per output element), transcendental element counts, parameter /
input / output / activation bytes, and fusion statistics (maximal
def-use-connected groups of elementwise ops — the metric "Operator
Fusion in XLA" (arXiv 2301.13062) shows tracks realized performance).
Every count is a deterministic function of the traced graph, so two runs
of the same code produce byte-identical tables — the property the CI
``perf-proxy`` gate (``bench.py --proxy`` vs the banked
``PERF_PROXY.json``) relies on.

Entry points::

    rep = mx.analysis.hlo.cost(model, sample_args)   # CostReport
    rep.model_flops_per_step()                       # derived headline
    print(rep.text_table())                          # mxlint --hlo --cost

The same numbers surface as an informational MX707 diagnostic per graph
when the ``hlo_cost`` pass runs with ``cost=True``
(``mx.analysis.hlo.verify(model, sample_args, cost=True)``) — opt-in so
staging gates stay signal-only by default.

Accounting rules (documented limits, all deterministic):

- ``scan`` bodies multiply execution metrics (FLOPs/transcendentals/
  activation bytes) by the trip count; ``while`` bodies count once (trip
  count unknowable statically — noted per graph); ``cond`` prices its
  costliest branch.
- fusion statistics are compile-time metrics: counted once per (sub-)
  jaxpr, never multiplied by trip counts.
- unknown primitives price one FLOP per output element and are tallied
  in ``unknown_eqns`` so a drifting jax version is visible, not silent.

Memory: :func:`peak_live_bytes` is a donation-aware last-use liveness
scan over the same jaxpr — args + consts + the maximal simultaneously-
live eqn outputs — the deterministic static twin of the runtime
``telemetry.memory`` ledger. It feeds the ``peak`` column of ``mxlint
--cost``, the banked ``peak_live_bytes`` perf-proxy gate, the autotune
memory-feasibility constraint, and the MX709 ``hlo_memory`` pass that
errors when a graph (or a whole bucket ladder,
:func:`ladder_peak_bytes`) exceeds ``MXTPU_HBM_BUDGET``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as onp

from ..diagnostics import Diagnostic
from .trace import TracedGraph, trace_entry

__all__ = ["GraphCost", "CostReport", "graph_cost", "cost_table", "cost",
           "peak_live_bytes", "ladder_peak_bytes", "hbm_budget_bytes"]


# -- primitive taxonomy ------------------------------------------------------
#: one transcendental evaluation per output element (counted separately —
#: TPUs run these on the slower special-function path)
_TRANSCENDENTAL = frozenset({
    "exp", "exp2", "log", "log2", "log1p", "expm1", "tanh", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "asinh",
    "acosh", "atanh", "erf", "erfc", "erf_inv", "logistic", "pow",
    "rsqrt", "sqrt", "cbrt", "digamma", "lgamma", "igamma", "igammac",
})

#: one FLOP per output element
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "neg", "abs",
    "sign", "floor", "ceil", "round", "clamp", "select_n", "and", "or",
    "xor", "not", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "eq", "ne", "ge", "gt", "le", "lt",
    "nextafter", "is_finite", "square", "reciprocal", "integer_pow",
    "add_any", "real", "imag", "conj", "complex", "population_count",
    "clz", "random_bits",
})

#: one FLOP per *input* element (a reduction reads everything once)
_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
    "reduce_window_sum", "reduce_window_max", "reduce_window_min",
})

#: zero FLOPs — data movement / relabeling XLA lowers to copies or elides
_MOVEMENT = frozenset({
    "reshape", "broadcast_in_dim", "transpose", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "rev", "gather",
    "scatter", "scatter-add", "scatter_add", "squeeze", "expand_dims",
    "iota", "convert_element_type", "bitcast_convert_type",
    "stop_gradient", "split", "sort", "top_k", "copy", "device_put",
    "random_seed", "random_wrap", "random_fold_in", "random_unwrap",
    "reduce_precision", "sharding_constraint", "broadcast",
})

#: eqns XLA's fusion pass can merge with their producers/consumers; a
#: def-use-connected group of these lowers to ~one fused kernel
_FUSIBLE = (_TRANSCENDENTAL | _ELEMENTWISE
            | frozenset({"broadcast_in_dim", "convert_element_type",
                         "reshape", "iota", "copy", "reduce_precision"}))

#: explicit collective primitives (shard_map / pmap regions) → verb.
#: Priced per device with the standard ring-algorithm byte counts.
_COLLECTIVE_VERBS = {
    "psum": "all_reduce", "pmax": "all_reduce", "pmin": "all_reduce",
    "all_gather": "all_gather",
    "psum_scatter": "reduce_scatter", "reduce_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute", "collective_permute": "ppermute",
}


def _collective_axes(eqn) -> tuple:
    """Named mesh axes a collective eqn reduces/gathers over."""
    ax = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(ax, (str, int)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def _spec_axes(spec) -> frozenset:
    """Mesh axis names a PartitionSpec partitions over."""
    if spec is None:
        return frozenset()
    axes = set()
    for e in tuple(spec):
        if e is None:
            continue
        for a in ((e,) if isinstance(e, str) else tuple(e)):
            axes.add(a)
    return frozenset(axes)


def _is_literal(v) -> bool:
    return type(v).__name__ == "Literal"


def _elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 1
    return int(onp.prod(shape, dtype=onp.int64)) if len(shape) else 1


def _nbytes(aval) -> int:
    try:
        d = onp.dtype(aval.dtype)
    except (TypeError, AttributeError):
        return 0                      # extended dtypes (PRNG keys)
    return _elems(aval) * d.itemsize


@dataclass
class GraphCost:
    """One traced graph priced. ``flops`` is per executed call — for a
    ``kind == "train"`` graph that IS the model-FLOPs-per-step."""

    entry: str
    site: str
    kind: str = "infer"
    flops: float = 0.0
    matmul_flops: float = 0.0        # dot_general + conv share of flops
    transcendentals: int = 0         # transcendental element evaluations
    param_bytes: int = 0
    input_bytes: int = 0
    output_bytes: int = 0
    #: every eqn output's bytes (trip-multiplied) — a memory-TRAFFIC
    #: proxy, NOT residency: values that die immediately still count.
    #: Residency is :attr:`peak_live_bytes` (the liveness scan).
    activation_bytes: int = 0
    #: deterministic peak live device bytes over one executed call:
    #: non-donated args + consts resident for the whole call, plus the
    #: maximal simultaneously-live set of eqn outputs under a
    #: last-use liveness scan (donated inputs die at their last use —
    #: the donation credit). An upper-bound residency model: XLA's
    #: buffer-assignment reuse can only come in under it.
    peak_live_bytes: int = 0
    eqns: int = 0
    fusible_eqns: int = 0
    fusion_groups: int = 0           # def-use components of fusible eqns
    fusion_candidates: int = 0       # groups of >= 2 eqns (real fusions)
    unknown_eqns: int = 0
    #: collective verb → executed count: explicit shard_map/pmap prims in
    #: the jaxpr PLUS, for a mesh-configured train graph, the implied SPMD
    #: gradient exchange (all-reduce over ``dp``; reduce-scatter +
    #: all-gather under ZeRO-1) derived from the in-resource specs
    collective_ops: Dict[str, int] = field(default_factory=dict)
    #: per-device communication bytes per executed call (ring-algorithm
    #: accounting: all-reduce 2(N-1)/N·B, gather/scatter (N-1)/N·B)
    comm_bytes: float = 0.0
    notes: List[str] = field(default_factory=list)

    @property
    def label(self) -> str:
        return f"{self.entry}[{self.site}]"

    @property
    def bytes_per_step(self) -> int:
        """Memory-TRAFFIC floor per call: params + inputs + outputs —
        bytes the call must at minimum move through HBM, not bytes it
        must simultaneously hold. Residency (what OOMs a chip) is
        :attr:`peak_live_bytes`; ``activation_bytes`` is likewise a
        traffic proxy (every eqn output, even values that die
        immediately), kept byte-identical to the banked PERF_PROXY
        families."""
        return self.param_bytes + self.input_bytes + self.output_bytes

    def to_dict(self) -> dict:
        return {
            "entry": self.entry, "site": self.site, "kind": self.kind,
            "flops": float(self.flops),
            "matmul_flops": float(self.matmul_flops),
            "transcendentals": int(self.transcendentals),
            "param_bytes": int(self.param_bytes),
            "input_bytes": int(self.input_bytes),
            "output_bytes": int(self.output_bytes),
            "activation_bytes": int(self.activation_bytes),
            "peak_live_bytes": int(self.peak_live_bytes),
            "bytes_per_step": int(self.bytes_per_step),
            "eqns": int(self.eqns),
            "fusible_eqns": int(self.fusible_eqns),
            "fusion_groups": int(self.fusion_groups),
            "fusion_candidates": int(self.fusion_candidates),
            "unknown_eqns": int(self.unknown_eqns),
            "collective_ops": {k: int(v)
                               for k, v in sorted(self.collective_ops.items())},
            "comm_bytes": int(self.comm_bytes),
            "notes": list(self.notes),
        }


# -- jaxpr walk --------------------------------------------------------------

def _sub_jaxprs(eqn):
    from .trace import _jaxprs_in
    for v in eqn.params.values():
        yield from _jaxprs_in(v)


def _fusion_stats(jaxpr):
    """(fusible_eqns, fusion_groups, fusion_candidates) at ONE jaxpr
    level: union-find over fusible eqns connected by def-use edges."""
    fusible = [i for i, e in enumerate(jaxpr.eqns)
               if e.primitive.name in _FUSIBLE]
    if not fusible:
        return 0, 0, 0
    idx = set(fusible)
    parent = {i: i for i in fusible}

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    producer = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for o in eqn.outvars:
            producer[o] = i
    for i in fusible:
        for v in jaxpr.eqns[i].invars:
            if _is_literal(v):
                continue
            j = producer.get(v)
            if j is not None and j in idx:
                parent[find(i)] = find(j)
    sizes: Dict[int, int] = {}
    for i in fusible:
        r = find(i)
        sizes[r] = sizes.get(r, 0) + 1
    groups = len(sizes)
    candidates = sum(1 for s in sizes.values() if s >= 2)
    return len(fusible), groups, candidates


def _axis_prod(axes: tuple, mesh_axes: Optional[Dict[str, int]]) -> int:
    """Product of the named axis sizes, 0 when any size is unknown."""
    n = 1
    for a in axes:
        size = (mesh_axes or {}).get(a)
        if not size:
            return 0
        n *= size
    return n


def _comm_into(verb: str, nbytes: float, n: int, count: float,
               acc: dict) -> None:
    """Accumulate one collective: ring-algorithm per-device bytes —
    all-reduce moves 2(N-1)/N·B, gather/scatter-family (N-1)/N·B,
    ppermute B. Unknown axis size (n=0) prices the full payload."""
    factor = (n - 1) / n if n > 1 else (0.0 if n == 1 else 1.0)
    if verb == "all_reduce":
        factor *= 2.0
    if verb == "ppermute":
        factor = 1.0
    acc["collectives"][verb] = acc["collectives"].get(verb, 0) + count
    acc["comm_bytes"] += factor * nbytes * count


def _eqn_into(eqn, mul: float, acc: dict,
              mesh_axes: Optional[Dict[str, int]] = None) -> None:
    name = eqn.primitive.name
    out_elems = sum(_elems(o.aval) for o in eqn.outvars
                    if hasattr(o, "aval"))
    out_bytes = sum(_nbytes(o.aval) for o in eqn.outvars
                    if hasattr(o, "aval"))
    if name in _COLLECTIVE_VERBS:
        verb = _COLLECTIVE_VERBS[name]
        n = _axis_prod(_collective_axes(eqn), mesh_axes)
        payload = out_bytes
        if verb == "reduce_scatter":      # input is the full array
            payload = sum(_nbytes(v.aval) for v in eqn.invars
                          if not _is_literal(v) and hasattr(v, "aval"))
        _comm_into(verb, payload, n, mul, acc)
        acc["activation_bytes"] += out_bytes * mul
        acc["eqns"] += 1
        return
    flops = 0.0
    if name == "dot_general":
        (lc, _rc), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        contract = 1
        for d in lc:
            contract *= int(lhs.shape[d])
        flops = 2.0 * out_elems * contract
        acc["matmul_flops"] += flops * mul
    elif name == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval
        rhs_spec = dn.rhs_spec          # (out_ch, in_ch/groups, *spatial)
        in_ch = int(rhs.shape[rhs_spec[1]])
        ksp = 1
        for d in rhs_spec[2:]:
            ksp *= int(rhs.shape[d])
        flops = 2.0 * out_elems * in_ch * ksp
        acc["matmul_flops"] += flops * mul
    elif name in _TRANSCENDENTAL:
        flops = float(out_elems)
        acc["transcendentals"] += int(out_elems * mul)
    elif name in _ELEMENTWISE:
        flops = float(out_elems)
    elif name in _REDUCE:
        ins = [v for v in eqn.invars
               if not _is_literal(v) and hasattr(v, "aval")]
        flops = float(_elems(ins[0].aval)) if ins else float(out_elems)
    elif name in _MOVEMENT:
        flops = 0.0
    else:
        flops = float(out_elems)
        acc["unknown_eqns"] += 1
    acc["flops"] += flops * mul
    acc["activation_bytes"] += out_bytes * mul
    acc["eqns"] += 1


def _closed_to_open(j):
    return j.jaxpr if hasattr(j, "jaxpr") and hasattr(j, "consts") else j


def _walk_jaxpr(jaxpr, mul: float, acc: dict,
                mesh_axes: Optional[Dict[str, int]] = None) -> None:
    fus = _fusion_stats(jaxpr)
    acc["fusible_eqns"] += fus[0]
    acc["fusion_groups"] += fus[1]
    acc["fusion_candidates"] += fus[2]
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "scan":
            length = int(eqn.params.get("length", 1))
            _walk_jaxpr(_closed_to_open(eqn.params["jaxpr"]),
                        mul * max(length, 1), acc, mesh_axes)
            continue
        if name == "while":
            _walk_jaxpr(_closed_to_open(eqn.params["body_jaxpr"]), mul, acc,
                        mesh_axes)
            _walk_jaxpr(_closed_to_open(eqn.params["cond_jaxpr"]), mul, acc,
                        mesh_axes)
            note = "while body priced for one trip (count unknowable)"
            if note not in acc["notes"]:
                acc["notes"].append(note)
            continue
        if name == "cond":
            branches = eqn.params.get("branches", ())
            best = None
            for b in branches:
                sub = _fresh_acc()
                _walk_jaxpr(_closed_to_open(b), mul, sub, mesh_axes)
                if best is None or sub["flops"] > best["flops"]:
                    best = sub
            if best is not None:
                for k, v in best.items():
                    if k == "notes":
                        acc["notes"].extend(n for n in v
                                            if n not in acc["notes"])
                    elif k == "collectives":
                        for verb, c in v.items():
                            acc[k][verb] = acc[k].get(verb, 0) + c
                    else:
                        acc[k] += v
            continue
        subs = list(_sub_jaxprs(eqn))
        if subs:                      # pjit / remat / custom_*_call bodies
            for s in subs:
                _walk_jaxpr(s, mul, acc, mesh_axes)
            continue
        _eqn_into(eqn, mul, acc, mesh_axes)


def _fresh_acc() -> dict:
    return {"flops": 0.0, "matmul_flops": 0.0, "transcendentals": 0,
            "activation_bytes": 0, "eqns": 0, "fusible_eqns": 0,
            "fusion_groups": 0, "fusion_candidates": 0, "unknown_eqns": 0,
            "collectives": {}, "comm_bytes": 0.0, "notes": []}


def _implied_spmd_comm(g: TracedGraph, acc: dict) -> None:
    """Price the gradient exchange XLA's SPMD partitioner inserts at
    compile time (invisible in the jaxpr): for a train graph on a mesh
    with a real ``dp`` axis, every ``dp``-replicated parameter's gradient
    is all-reduced over ``dp`` — or, when its optimizer states are
    ``dp``-partitioned (ZeRO-1), reduce-scattered into the sharded update
    with the new weight all-gathered back. Both move the same
    2(N-1)/N·B bytes; only the verb split differs. Deterministic: a pure
    function of the in-resource specs and the mesh axis sizes."""
    dp = (g.mesh_axes or {}).get("dp", 1)
    if g.kind != "train" or dp <= 1 or not g.in_specs:
        return
    zero1 = any(r == "state" and "dp" in _spec_axes(s)
                for r, s in zip(g.roles, g.in_specs))
    jaxpr = g.closed.jaxpr
    priced = 0
    for v, role, spec in zip(jaxpr.invars, g.roles, g.in_specs):
        if role != "param" or "dp" in _spec_axes(spec):
            continue                  # dp-sharded params exchange no grad
        b = _nbytes(v.aval)
        if not b:
            continue
        priced += 1
        if zero1:
            _comm_into("reduce_scatter", b, dp, 1.0, acc)
            _comm_into("all_gather", b, dp, 1.0, acc)
        else:
            _comm_into("all_reduce", b, dp, 1.0, acc)
    if priced:
        acc["notes"].append(
            f"implied SPMD gradient exchange priced for {priced} "
            f"parameter(s) over dp={dp}"
            + (" (zero1: reduce-scatter + all-gather)" if zero1 else
               " (all-reduce)"))


# -- liveness: peak resident device bytes ------------------------------------

def _donated_mask(g: TracedGraph) -> tuple:
    n = len(g.closed.jaxpr.invars)
    d = g.donated or ()
    return tuple(bool(d[i]) if i < len(d) else False for i in range(n))


def _inner_extra(eqn) -> int:
    """Transient scratch an eqn's sub-jaxprs (pjit/remat/scan/cond
    bodies) need beyond the eqn's own operands: the sub-graph's peak
    minus its invar bytes (those alias buffers already live in the
    enclosing frame). Counted once — residency is a max, never a sum
    over trips — so a scan body's scratch is NOT trip-multiplied."""
    extra = 0
    for sub in _sub_jaxprs(eqn):
        in_b = sum(_nbytes(v.aval) for v in sub.invars
                   if hasattr(v, "aval"))
        extra = max(extra, max(0, _open_jaxpr_peak(sub, ()) - in_b))
    return extra


def _open_jaxpr_peak(jaxpr, donated: tuple) -> int:
    """Last-use liveness scan over one (open) jaxpr, in bytes.

    Residency model, a deterministic pure function of the jaxpr:

    - non-donated invars are resident for the WHOLE call (the caller
      retains those buffers) — so are constvars (trace-time constants
      XLA materializes on device);
    - donated invars die after their last use (the donation credit —
      XLA may alias the buffer into an output);
    - each eqn's outputs are allocated while its inputs are still live
      (an executing kernel holds both), then freed after their own last
      use; jaxpr outvars live to the end of the call;
    - an eqn with sub-jaxprs additionally holds the sub-graph's
      transient scratch (:func:`_inner_extra`) while it runs.
    """
    n_eqns = len(jaxpr.eqns)
    last_use: Dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last_use[v] = n_eqns          # outputs survive the call
    fixed = sum(_nbytes(v.aval) for v in getattr(jaxpr, "constvars", ())
                if hasattr(v, "aval"))
    live: Dict = {}                       # var -> bytes, dies at last use
    for i, v in enumerate(jaxpr.invars):
        b = _nbytes(v.aval) if hasattr(v, "aval") else 0
        if i < len(donated) and donated[i]:
            live[v] = b
        else:
            fixed += b
    live_b = sum(live.values())
    peak = fixed + live_b
    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(_nbytes(o.aval) for o in eqn.outvars
                    if hasattr(o, "aval"))
        peak = max(peak, fixed + live_b + out_b + _inner_extra(eqn))
        for o in eqn.outvars:
            if last_use.get(o, -1) > i:   # value someone later reads
                b = _nbytes(o.aval) if hasattr(o, "aval") else 0
                live[o] = b
                live_b += b
        for v in eqn.invars:
            if not _is_literal(v) and last_use.get(v) == i and v in live:
                live_b -= live.pop(v)
    return int(peak)


def peak_live_bytes(g: TracedGraph) -> int:
    """Deterministic peak live device bytes of one traced graph —
    args + consts + the maximal simultaneously-live eqn outputs under a
    donation-aware last-use liveness scan. Zero XLA compiles; same
    graph → same number, the property the MX709 budget gate and the
    banked PERF_PROXY ``peak_live_bytes`` rely on."""
    return _open_jaxpr_peak(g.closed.jaxpr, _donated_mask(g))


def _graph_param_bytes(g: TracedGraph) -> int:
    return sum(_nbytes(v.aval)
               for v, role in zip(g.closed.jaxpr.invars, g.roles)
               if role in ("param", "state") and hasattr(v, "aval"))


def _ladder_from_pairs(pairs) -> int:
    """THE ladder accounting, over ``(param_bytes, peak_bytes)`` pairs:
    parameters counted once (max — weights are shared across bucket
    executables), every graph's non-parameter residency summed. Shared
    by :func:`ladder_peak_bytes` (TracedGraphs) and
    :meth:`CostReport.ladder_peak_bytes` (priced rows) so the staging
    preflight and the banked proxy can never disagree."""
    pairs = list(pairs)
    if not pairs:
        return 0
    params = max(pb for pb, _ in pairs)
    rest = sum(max(0, peak - pb) for pb, peak in pairs)
    return int(params + rest)


def ladder_peak_bytes(graphs: List[TracedGraph]) -> int:
    """Conservative resident footprint of a whole bucket LADDER (one
    entry's graphs held on device at once): the parameter/state set
    counted ONCE (weights are shared across bucket executables) plus
    every bucket's non-parameter residency summed — each warmed bucket
    retains its own donated request buffers, outputs, and executable
    scratch. This is the number the serve staging preflight checks
    against ``MXTPU_HBM_BUDGET``: buckets execute one at a time, but
    they stay RESIDENT together."""
    return _ladder_from_pairs((_graph_param_bytes(g), peak_live_bytes(g))
                              for g in graphs)


def hbm_budget_bytes() -> Optional[int]:
    """``MXTPU_HBM_BUDGET`` in bytes, or ``None`` when unset — a
    re-export of :func:`~...util.hbm_budget_bytes` (the ONE budget read
    every gate shares) at the analysis surface."""
    from ...util import hbm_budget_bytes as _budget
    return _budget()


def _fmt_mib(n: int) -> str:
    return f"{n / 2**20:.1f} MiB"


def graph_cost(g: TracedGraph) -> GraphCost:
    """Price one :class:`~.trace.TracedGraph` — THE cost function every
    surface (``analysis.hlo.cost``, the MX707 pass, ``mxlint --cost``,
    ``bench.py --proxy``) shares, so they can never disagree."""
    jaxpr = g.closed.jaxpr
    acc = _fresh_acc()
    _walk_jaxpr(jaxpr, 1.0, acc, g.mesh_axes)
    _implied_spmd_comm(g, acc)
    param_bytes = input_bytes = 0
    for v, role in zip(jaxpr.invars, g.roles):
        if role in ("param", "state"):
            param_bytes += _nbytes(v.aval)
        elif role == "input":
            input_bytes += _nbytes(v.aval)
    output_bytes = sum(_nbytes(o.aval) for o in jaxpr.outvars
                       if hasattr(o, "aval"))
    return GraphCost(
        entry=g.entry, site=g.site, kind=g.kind,
        flops=acc["flops"], matmul_flops=acc["matmul_flops"],
        transcendentals=acc["transcendentals"],
        param_bytes=param_bytes, input_bytes=input_bytes,
        output_bytes=output_bytes,
        activation_bytes=int(acc["activation_bytes"]),
        peak_live_bytes=peak_live_bytes(g),
        eqns=acc["eqns"], fusible_eqns=acc["fusible_eqns"],
        fusion_groups=acc["fusion_groups"],
        fusion_candidates=acc["fusion_candidates"],
        unknown_eqns=acc["unknown_eqns"],
        collective_ops={k: int(round(v))
                        for k, v in sorted(acc["collectives"].items())},
        comm_bytes=float(acc["comm_bytes"]),
        notes=acc["notes"])


def cost_table(graphs: List[TracedGraph]) -> List[GraphCost]:
    return [graph_cost(g) for g in graphs]


@dataclass
class CostReport:
    """Cost rows for every traced graph of one entry, plus the derived
    headline metrics the perf-proxy gate banks."""

    rows: List[GraphCost] = field(default_factory=list)
    skipped: List[str] = field(default_factory=list)

    @property
    def head(self) -> Optional[GraphCost]:
        """The costliest graph — for a bucketed serving model the largest
        bucket, for a trainer the step graph."""
        return max(self.rows, key=lambda r: r.flops) if self.rows else None

    def model_flops_per_step(self) -> float:
        """Derived model-FLOPs-per-step: the costliest graph's FLOPs (one
        executed step/call runs exactly one bucket's executable)."""
        return float(self.head.flops) if self.rows else 0.0

    def bytes_per_step(self) -> int:
        return int(self.head.bytes_per_step) if self.rows else 0

    def peak_live_bytes(self) -> int:
        """Deterministic peak live device bytes: the WORST graph's peak
        (one executed step/call runs one executable, so the largest
        bucket / the step graph sets the high-water mark)."""
        return max((int(r.peak_live_bytes) for r in self.rows), default=0)

    def ladder_peak_bytes(self) -> int:
        """Conservative whole-ladder resident footprint — the SAME
        :func:`_ladder_from_pairs` accounting as the module-level
        :func:`ladder_peak_bytes`, derived from the priced rows so
        callers holding only a CostReport need not re-trace."""
        return _ladder_from_pairs((r.param_bytes, r.peak_live_bytes)
                                  for r in self.rows)

    def comm_bytes_per_step(self) -> int:
        """Per-device collective communication bytes of the costliest
        graph (explicit collective prims + implied SPMD gradient
        exchange) — 0 for a single-device graph."""
        return int(self.head.comm_bytes) if self.rows else 0

    def collective_ops_per_step(self) -> int:
        return (sum(self.head.collective_ops.values())
                if self.rows else 0)

    def to_dict(self) -> dict:
        return {"rows": [r.to_dict() for r in self.rows],
                "model_flops_per_step": self.model_flops_per_step(),
                "bytes_per_step": self.bytes_per_step(),
                "peak_live_bytes": self.peak_live_bytes(),
                "ladder_peak_bytes": self.ladder_peak_bytes(),
                "comm_bytes_per_step": self.comm_bytes_per_step(),
                "collective_ops_per_step": self.collective_ops_per_step(),
                "skipped": list(self.skipped)}

    def text_table(self) -> str:
        """Aligned human table (``mxlint --hlo <t> --cost``)."""
        hdr = (f"{'graph':<40} {'kind':<6} {'MFLOP':>10} {'mm%':>5} "
               f"{'trans':>8} {'par KiB':>9} {'act KiB':>9} "
               f"{'peak KiB':>9} "
               f"{'io KiB':>9} {'comm KiB':>9} {'coll':>4} {'eqns':>5} "
               f"{'fus':>4} {'grp':>4} {'cand':>4}")
        lines = [hdr, "-" * len(hdr)]
        for r in self.rows:
            mm = 100.0 * r.matmul_flops / r.flops if r.flops else 0.0
            io_kib = (r.input_bytes + r.output_bytes) >> 10
            lines.append(
                f"{r.label:<40} {r.kind:<6} {r.flops / 1e6:>10.3f} "
                f"{mm:>5.1f} {r.transcendentals:>8} "
                f"{r.param_bytes >> 10:>9} {r.activation_bytes >> 10:>9} "
                f"{r.peak_live_bytes >> 10:>9} "
                f"{io_kib:>9} {int(r.comm_bytes) >> 10:>9} "
                f"{sum(r.collective_ops.values()):>4} "
                f"{r.eqns:>5} {r.fusible_eqns:>4} "
                f"{r.fusion_groups:>4} {r.fusion_candidates:>4}")
        if self.rows:
            lines.append(
                f"model_flops_per_step={self.model_flops_per_step():.6g} "
                f"bytes_per_step={self.bytes_per_step()} "
                f"peak_live_bytes={self.peak_live_bytes()} "
                f"ladder_peak_bytes={self.ladder_peak_bytes()} "
                f"comm_bytes_per_step={self.comm_bytes_per_step()}")
        for s in self.skipped:
            lines.append(f"note: skipped {s}")
        return "\n".join(lines)


def cost(model, sample_args=None, max_graphs: int = 8) -> CostReport:
    """Trace ``model`` (same dispatch as :func:`~..verify`: CompiledModel
    buckets, SymbolBlock signatures, ShardedTrainer step, HybridBlock,
    plain callable) and price every traced graph. Never XLA-compiles."""
    result = trace_entry(model, sample_args, max_graphs=max_graphs)
    return CostReport(rows=cost_table(result.graphs),
                      skipped=list(result.skipped))


# -- the informational MX707 pass -------------------------------------------

def _register():
    from .passes import register_hlo_pass

    @register_hlo_pass("hlo_cost",
                       describe="per-graph cost table (FLOPs, bytes, "
                                "transcendentals, fusion groups) as "
                                "informational MX707 rows — opt-in via "
                                "cost=True")
    def hlo_cost(ctx) -> None:
        """Informational per-graph cost rows (MX707). Opt-in: runs only
        when the pass context carries ``cost=True``
        (``verify(model, args, cost=True)`` / ``mxlint --hlo --cost``),
        so staging gates stay signal-only by default."""
        if not ctx.opt("cost", False):
            return
        for g in ctx.graphs:
            c = graph_cost(g)
            coll = (f", {int(c.comm_bytes) >> 10} KiB comm over "
                    f"{sum(c.collective_ops.values())} collective(s) "
                    f"({', '.join(f'{k}x{v}' for k, v in sorted(c.collective_ops.items()))})"
                    if c.collective_ops else "")
            ctx.diag(
                "MX707",
                f"cost: {c.flops:.6g} FLOPs ({c.matmul_flops:.6g} matmul), "
                f"{c.transcendentals} transcendental elems, "
                f"{c.param_bytes >> 10} KiB params, "
                f"{c.activation_bytes >> 10} KiB activations, "
                f"{c.peak_live_bytes >> 10} KiB peak live, "
                f"{c.input_bytes + c.output_bytes >> 10} KiB in+out, "
                f"{c.eqns} eqns, {c.fusible_eqns} fusible in "
                f"{c.fusion_groups} group(s) "
                f"({c.fusion_candidates} multi-op){coll}", g, severity="info")

    @register_hlo_pass("hlo_memory",
                       describe="peak live device memory exceeds "
                                "MXTPU_HBM_BUDGET (donation-aware jaxpr "
                                "liveness scan; whole bucket ladders "
                                "checked too), MX709")
    def hlo_memory(ctx) -> None:
        """The memory budget gate (MX709): each graph's deterministic
        ``peak_live_bytes`` — and each entry's summed bucket-ladder
        residency — must fit ``MXTPU_HBM_BUDGET`` (or the explicit
        ``hbm_budget_bytes`` pass option). Silent when no budget is
        configured, so un-budgeted runs and the clean fixtures see zero
        findings; with a budget set it is error severity and aborts
        serve staging exactly like MX701/MX705."""
        budget = ctx.opt("hbm_budget_bytes", None)
        if budget is None:
            budget = hbm_budget_bytes()
        if not budget:
            return
        by_entry: Dict[str, list] = {}
        for g in ctx.graphs:
            peak = peak_live_bytes(g)
            by_entry.setdefault(g.entry, []).append((g, peak))
            if peak > budget:
                ctx.diag(
                    "MX709",
                    f"peak live device memory {_fmt_mib(peak)} exceeds "
                    f"the HBM budget {_fmt_mib(int(budget))} "
                    f"(MXTPU_HBM_BUDGET): this graph cannot fit on the "
                    "chip — shrink the batch/bucket geometry, enable "
                    "remat, or raise the budget", g, severity="error")
        for entry, rows in by_entry.items():
            if len(rows) < 2 or any(p > budget for _, p in rows):
                continue          # per-graph findings already tell the story
            ladder = _ladder_from_pairs(          # peaks already scanned
                (_graph_param_bytes(g), p) for g, p in rows)
            if ladder > budget:
                ctx.diag(
                    "MX709",
                    f"bucket ladder holds {_fmt_mib(ladder)} resident "
                    f"across {len(rows)} warmed bucket(s) — over the "
                    f"HBM budget {_fmt_mib(int(budget))} even though "
                    "every bucket fits alone (weights counted once, "
                    "per-bucket buffers summed): trim the bucket table "
                    "or raise the budget",
                    node=f"{entry}[ladder]", severity="error")


_register()

"""Sharding-consistency pass (pass 4).

Reference counterpart: the kvstore's layout decisions were runtime code
paths that failed loudly; here a layout is a *declarative*
``(regex -> PartitionSpec)`` table (``parallel/sharding.py``) matched
against a named mesh (``parallel/mesh.py``) — and a typo'd axis name or a
rank-mismatched spec silently degrades to replicated (``spec_for`` falls
back to ``P()``), which trains correctly but N× slower. This pass makes
those silent fallbacks visible:

- **MX301** a spec names an axis the mesh does not declare,
- **MX302** spec rank exceeds the parameter rank, or the mesh axes don't
  divide the dimension (warning: legal, but silently replicates),
- **MX303** conflicting specs — the same pattern registered twice with
  different specs (error), or one parameter matched by several rules with
  different specs where only the first wins (warning).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from .diagnostics import Diagnostic, Report
from .passes import PassContext, register_pass

__all__ = ["check_sharding"]


def _spec_axes(spec):
    """Flat axis-name list of a PartitionSpec entry tuple."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            out.append(a)
    return out


def check_sharding(rules, mesh,
                   params: Optional[Dict[str, Tuple[int, ...]]] = None,
                   ) -> Report:
    """Validate a :class:`~incubator_mxnet_tpu.parallel.sharding.ShardingRules`
    table against ``mesh`` and (optionally) concrete parameter shapes."""
    report = Report()
    axis_names = set(mesh.axis_names)
    seen_patterns: Dict[str, object] = {}
    for pat, spec in rules._rules:
        for axis in _spec_axes(spec):
            if axis not in axis_names:
                report.add(Diagnostic(
                    "MX301",
                    f"spec {spec} names mesh axis {axis!r}, but the mesh "
                    f"declares {sorted(axis_names)}",
                    node=pat.pattern, op="sharding_rule",
                    pass_name="sharding"))
        if pat.pattern in seen_patterns and \
                seen_patterns[pat.pattern] != spec:
            report.add(Diagnostic(
                "MX303",
                f"pattern registered twice with different specs: "
                f"{seen_patterns[pat.pattern]} vs {spec} (first wins)",
                node=pat.pattern, op="sharding_rule", pass_name="sharding"))
        seen_patterns.setdefault(pat.pattern, spec)

    for name, shape in (params or {}).items():
        shape = tuple(shape)
        matches = [(pat, spec) for pat, spec in rules._rules
                   if pat.search(name)]
        if not matches:
            continue
        distinct = []
        for _, spec in matches:
            if spec not in distinct:
                distinct.append(spec)
        if len(distinct) > 1:
            report.add(Diagnostic(
                "MX303",
                f"matched by {len(matches)} rules with different specs "
                f"{distinct}; first ({distinct[0]}) wins",
                node=name, op="param", pass_name="sharding",
                severity="warning"))
        pat, spec = matches[0]
        entries = tuple(spec)
        if len(entries) > len(shape):
            report.add(Diagnostic(
                "MX302",
                f"spec {spec} has rank {len(entries)} but parameter shape "
                f"{shape} has rank {len(shape)}; spec_for silently "
                "replicates this parameter",
                node=name, op="param", pass_name="sharding"))
            continue
        for dim, entry in zip(shape, entries):
            if entry is None:
                continue
            size = 1
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                size *= mesh.shape.get(a, 1)
            if size and dim % size:
                report.add(Diagnostic(
                    "MX302",
                    f"dim {dim} not divisible by mesh axes {entry} "
                    f"(size {size}); spec_for silently replicates this "
                    "parameter",
                    node=name, op="param", pass_name="sharding",
                    severity="warning"))
    return report


@register_pass("sharding",
               describe="PartitionSpec vs mesh-axis consistency "
                        "(MX301-MX303)")
def _sharding_pass(ctx: PassContext) -> None:
    if ctx.rules is None or ctx.mesh is None:
        ctx.report.skipped.append(
            "sharding: needs rules= and mesh= (pass them to verify())")
        return
    ctx.report.extend(check_sharding(ctx.rules, ctx.mesh, ctx.params))

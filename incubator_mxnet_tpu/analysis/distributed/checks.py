"""AST implementations of the MX901–MX904 distributed-correctness passes.

The multi-controller SPMD contract has two halves, and the two central
passes here are each other's inverse:

- **MX901**: code every process must run identically (collective issues,
  jitted-graph builds/dispatches, kvstore traffic) must NOT sit under
  host-conditional control flow — the processes that skip the branch
  never reach the collective and the pod hangs.
- **MX902**: code that touches the shared filesystem (checkpoints,
  telemetry exports, artifact caches) MUST diverge — exactly one elected
  host writes, the rest no-op — or N hosts race the same rename.

MX903 (non-elastic world assumptions frozen at import time) and MX904
(cross-host RNG divergence) round out the family; MX905, the HLO-layer
collective-schedule pass, lives in :mod:`.schedule` because it runs over
traced graphs rather than source.

Awareness scoping: MX902/MX904 only fire in *multi-host-aware* files —
files that already reference the process topology (``process_index``/
``process_count``/``is_primary``, ``jax.distributed``, the
``parallel.dist`` shim, or dmlc rank env vars). A single-host utility
writing a local file is not an SPMD hazard; the moment the file learns
about the topology, its effects must be elected. MX901 and MX903 run
everywhere (a topology-conditional collective or an import-time world
size is hazardous wherever it appears).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..diagnostics import Diagnostic, Report

__all__ = ["DIST_PASSES", "check_source"]

#: pass name -> diagnostic code (the registry audit and the generated
#: docs read this table)
DIST_PASSES: Dict[str, str] = {
    "dist_collective_flow": "MX901",
    "dist_elected_effects": "MX902",
    "dist_elastic_world": "MX903",
    "dist_rng_divergence": "MX904",
    "hlo_collective_schedule": "MX905",
}

#: calls whose result identifies THIS process within the pod
_TOPOLOGY_CALLS = frozenset({"process_index", "process_count"})
#: rank/world env vars (dmlc lineage + the common launcher conventions)
_RANK_ENV_VARS = frozenset({
    "DMLC_WORKER_ID", "DMLC_NUM_WORKER", "DMLC_ROLE",
    "RANK", "WORLD_SIZE", "LOCAL_RANK", "NODE_RANK",
    "OMPI_COMM_WORLD_RANK", "SLURM_PROCID",
    "JAX_PROCESS_ID", "JAX_NUM_PROCESSES",
})
#: collective issues — every process on the mesh must reach these
_COLLECTIVE_CALLS = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_reduce",
    "psum_scatter", "reduce_scatter", "all_to_all", "ppermute",
    "collective_permute", "barrier",
})
#: jitted-graph builds/dispatches — a compile (and the executable it
#: produces) must exist on every process or the first dispatch hangs
_BUILD_CALLS = frozenset({
    "jit", "pjit", "lower", "compile", "make_jaxpr", "hybridize",
    "shard_map", "pmap", "step",
})
#: kvstore traffic — the ps-lite lineage's collective surface
_KVSTORE_CALLS = frozenset({"push", "pull", "pushpull", "broadcast"})
_MX901_HAZARDS = _COLLECTIVE_CALLS | _BUILD_CALLS | _KVSTORE_CALLS

#: names whose mention in an ``if`` test marks it as a host-0 election
#: guard (MX902's accepted idiom) — and as host-conditional flow (MX901)
_ELECTION_NAMES = frozenset({
    "is_primary", "process_index", "process_count", "primary", "host0",
    "elected",
})

#: import-time world-size reads (MX903)
_WORLD_CALLS = frozenset({"device_count", "local_device_count",
                          "process_count"})

#: global-stream draws from the process-local default RNG (MX904)
_GLOBAL_DRAWS = frozenset({
    "rand", "randn", "randint", "uniform", "normal", "random", "choice",
    "permutation", "shuffle", "standard_normal", "sample",
})
#: non-deterministic seed sources (MX904)
_TIME_SEEDS = frozenset({"time", "time_ns", "monotonic", "urandom",
                         "getrandbits", "perf_counter"})
#: seed plumbing that makes per-host streams intentional and reproducible
_SEED_FIXES = frozenset({"process_index", "fold_in", "random_fold_in",
                         "broadcast", "broadcast_one_to_all"})


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _tail(node) -> Optional[str]:
    """The last dotted component of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _call_tails(node) -> Set[str]:
    """Tails of every call inside ``node`` (the expression subtree)."""
    return {t for n in ast.walk(node) if isinstance(n, ast.Call)
            for t in [_tail(n.func)] if t}


def _name_tails(node) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        t = _tail(n) if isinstance(n, (ast.Name, ast.Attribute)) else None
        if t:
            out.add(t)
    return out


def _env_keys(node) -> Set[str]:
    """String keys read from ``os.environ[...]`` / ``environ.get(...)`` /
    ``os.getenv(...)`` anywhere inside ``node``."""
    keys: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Subscript) and _tail(n.value) == "environ":
            s = n.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                keys.add(s.value)
        elif isinstance(n, ast.Call):
            t = _tail(n.func)
            is_env_get = (t == "get"
                          and _tail(getattr(n.func, "value", None))
                          == "environ")
            if (t == "getenv" or is_env_get) and n.args:
                a = n.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    keys.add(a.value)
    return keys


def _mentions_topology(test) -> bool:
    """Does this ``if``/``while`` test read the process topology?"""
    if _call_tails(test) & _TOPOLOGY_CALLS:
        return True
    if _env_keys(test) & _RANK_ENV_VARS:
        return True
    return False


def _mentions_election(test) -> bool:
    return bool(_name_tails(test) & _ELECTION_NAMES) \
        or bool(_env_keys(test) & _RANK_ENV_VARS)


def _attach_parents(tree) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._mx_parent = node  # type: ignore[attr-defined]


def _ancestors(node):
    p = getattr(node, "_mx_parent", None)
    while p is not None:
        yield p
        p = getattr(p, "_mx_parent", None)


def _context_of(node) -> str:
    """``Class.method`` / function / ``<module>`` provenance label."""
    names: List[str] = []
    for a in _ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            names.append(a.name)
    return ".".join(reversed(names)) or "<module>"


def _enclosing_function(node):
    for a in _ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def _is_aware(tree) -> bool:
    """Multi-host-aware file: it references the process topology, the
    ``parallel.dist`` shim, or ``jax.distributed``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            t = _tail(node.func)
            if t in _TOPOLOGY_CALLS or t == "is_primary":
                return True
        if isinstance(node, ast.Attribute) and node.attr == "distributed" \
                and _tail(node.value) == "jax":
            return True
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("dist") or mod.endswith("distributed"):
                return True
            if any(a.name in ("dist", "is_primary") for a in node.names):
                return True
    for node in ast.walk(tree):
        if _env_keys(node) & _RANK_ENV_VARS:
            return True
    return False


# ---------------------------------------------------------------------------
# MX901 — host-conditional control flow over collectives/builds/kv traffic
# ---------------------------------------------------------------------------

def _check_collective_flow(tree, filename: str, report: Report) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if not _mentions_topology(node.test):
            continue
        # scan BOTH branches: either side reaching a collective while the
        # other does not is the asymmetry that hangs
        hazards: List[ast.Call] = []
        for stmt in list(node.body) + list(node.orelse):
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    t = _tail(n.func)
                    if t in _MX901_HAZARDS:
                        hazards.append(n)
        if not hazards:
            continue
        first = hazards[0]
        tails = sorted({_tail(h.func) for h in hazards})
        kind = ("while loop" if isinstance(node, ast.While)
                else "branch")
        report.add(Diagnostic(
            "MX901",
            f"host-conditional {kind} on the process topology encloses "
            f"{len(hazards)} collective/jit/kvstore call(s) "
            f"({', '.join(tails[:4])} at line {first.lineno}): in the "
            "multi-controller SPMD model every process must issue the "
            "same collective sequence — a host that skips this branch "
            "leaves the others blocked in the collective forever (a "
            "hang, not a crash). Elect effects, never collectives: keep "
            "graph builds and collective dispatches unconditional and "
            "put only filesystem/telemetry side effects behind "
            "process_index() guards",
            node=f"{filename}:{node.lineno}",
            op=_context_of(node), pass_name="dist_collective_flow"))


# ---------------------------------------------------------------------------
# MX902 — unelected persistent writes in multi-host-aware files
# ---------------------------------------------------------------------------

def _write_mode(call: ast.Call) -> Optional[str]:
    """The write-ish mode string of an ``open(...)`` call, or None.
    Handles conditional modes like ``"a" if started else "w"``."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None
    literals = [n.value for n in ast.walk(mode)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)]
    for lit in literals:
        if any(c in lit for c in "wax"):
            return lit
    return None


def _is_write_site(node: ast.Call) -> Optional[str]:
    t = _tail(node.func)
    if t in ("replace", "rename") and _tail(
            getattr(node.func, "value", None)) == "os":
        return f"os.{t}"
    if t == "open" and isinstance(node.func, ast.Name):
        m = _write_mode(node)
        if m is not None:
            return f"open(mode={m!r})"
    return None


def _guarded(node: ast.Call) -> bool:
    """Is this write dominated by a host-election test?  Accepted forms:
    an enclosing ``if`` whose test mentions election names, an earlier
    early-exit election guard in the same function, or an enclosing
    function that IS the election helper."""
    fn = _enclosing_function(node)
    if fn is not None and any(s in fn.name.lower()
                              for s in ("primary", "elect")):
        return True
    for a in _ancestors(node):
        if isinstance(a, ast.If) and _mentions_election(a.test):
            return True
    if fn is None:
        return False
    # early-exit guard: `if not is_primary(): return ...` before the write
    for stmt in fn.body:
        if stmt.lineno >= node.lineno:
            break
        if isinstance(stmt, ast.If) and _mentions_election(stmt.test) \
                and any(isinstance(s, (ast.Return, ast.Raise))
                        for s in stmt.body):
            return True
    return False


def _check_elected_effects(tree, filename: str, report: Report,
                           aware: bool) -> None:
    if not aware:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        what = _is_write_site(node)
        if what is None or _guarded(node):
            continue
        report.add(Diagnostic(
            "MX902",
            f"unelected persistent write ({what}) in a multi-host-aware "
            "module: under SPMD every process executes this line, so N "
            "hosts race the same file/rename on a shared filesystem — "
            "elect exactly one writer (guard with parallel.dist."
            "is_primary(), a no-op at process_count()==1) or, where "
            "per-host divergence is intentional (per-host forensics "
            "with pid-unique names), document it with an inline "
            "`# mxlint: disable=MX902`",
            node=f"{filename}:{node.lineno}",
            op=_context_of(node), pass_name="dist_elected_effects"))


# ---------------------------------------------------------------------------
# MX903 — world sizes frozen at import time
# ---------------------------------------------------------------------------

def _module_scope_stmts(tree):
    """Nodes that execute at import time: module-level simple statements,
    class bodies, and the import-time *headers* of module-level compound
    statements (an ``if`` test, ``with`` context expressions) — their
    bodies are queued individually rather than scanned wholesale, so a
    method inside a class (call-time) never leaks into the import-time
    set. Function bodies re-evaluate per call and are exempt."""
    stack = list(tree.body)
    while stack:
        stmt = stack.pop(0)
        if isinstance(stmt, ast.ClassDef):
            stack = list(stmt.body) + stack
        elif isinstance(stmt, ast.If):
            yield stmt.test
            stack = list(stmt.body) + list(stmt.orelse) + stack
        elif isinstance(stmt, ast.Try):
            body = list(stmt.body) + list(stmt.orelse) + list(stmt.finalbody)
            for h in stmt.handlers:
                body += list(h.body)
            stack = body + stack
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                yield item.context_expr
            stack = list(stmt.body) + stack
        else:
            yield stmt


def _world_reads(node) -> List[str]:
    """World-size reads inside ``node``: jax.devices()/device_count()/
    process_count() calls and rank/world env var reads."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            t = _tail(n.func)
            if t in _WORLD_CALLS:
                out.append(f"{t}()")
            elif t == "devices" and _tail(
                    getattr(n.func, "value", None)) == "jax":
                out.append("jax.devices()")
    env = _env_keys(node) & _RANK_ENV_VARS
    out.extend(sorted(env))
    return out


def _check_elastic_world(tree, filename: str, report: Report) -> None:
    def flag(node, reads: List[str], where: str) -> None:
        report.add(Diagnostic(
            "MX903",
            f"world size frozen at import time ({', '.join(reads[:3])} "
            f"in {where}): the value is evaluated when the module loads "
            "— before dist.initialize() has rendezvoused the pod — and "
            "an elastic restart with a different process/device count "
            "silently reuses the stale number; read the topology inside "
            "the function that builds the mesh/step instead",
            node=f"{filename}:{node.lineno}",
            op=_context_of(node), pass_name="dist_elastic_world"))

    for stmt in _module_scope_stmts(tree):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # the body is call-time; defaults handled below
        reads = _world_reads(stmt)
        if reads:
            flag(stmt, reads, "module scope")
    # default-argument expressions evaluate at def time == import time
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            reads = _world_reads(default)
            if reads:
                flag(node, reads, f"a default argument of {node.name}()")


# ---------------------------------------------------------------------------
# MX904 — cross-host RNG divergence
# ---------------------------------------------------------------------------

def _seed_fixed(call: ast.Call) -> bool:
    """Seed expression folds the process identity or is broadcast —
    per-host streams are then intentional and reproducible."""
    return bool(_call_tails(call) & _SEED_FIXES) \
        or bool(_name_tails(call) & _SEED_FIXES)


def _rng_hazard(call: ast.Call) -> Optional[str]:
    t = _tail(call.func)
    owner = _tail(getattr(call.func, "value", None))
    args = list(call.args) + [kw.value for kw in call.keywords]
    time_seeded = any(_call_tails(a) & _TIME_SEEDS for a in args)
    none_seeded = any(isinstance(a, ast.Constant) and a.value is None
                      for a in args)
    if t in ("PRNGKey", "key") and owner in ("random", "jax", None) \
            and args and time_seeded:
        return f"{t}() seeded from wall-clock time"
    if t in ("seed",) and owner in ("random", None):
        if not args or time_seeded or none_seeded:
            return "seed() with no/time-based seed (fresh OS entropy " \
                   "per host)"
    if t in ("RandomState", "default_rng", "Generator"):
        if not args or time_seeded or none_seeded:
            return f"{t}() with no/time-based seed"
    if t in _GLOBAL_DRAWS and owner == "random":
        return f"{owner}.{t}() draw from the unseeded process-local " \
               "default stream"
    return None


def _check_rng_divergence(tree, filename: str, report: Report,
                          aware: bool) -> None:
    if not aware:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        what = _rng_hazard(node)
        if what is None or _seed_fixed(node):
            continue
        report.add(Diagnostic(
            "MX904",
            f"cross-host RNG divergence: {what} in a multi-host-aware "
            "module — every process draws a different stream, so "
            "'identical' SPMD programs feed different batches or trace "
            "different graphs and the run diverges without any error; "
            "derive the seed deterministically and fold the process "
            "identity in where per-host streams are wanted "
            "(fold_in(key, process_index())) or broadcast one seed "
            "from host 0",
            node=f"{filename}:{node.lineno}",
            op=_context_of(node), pass_name="dist_rng_divergence"))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def check_source(src: str, filename: str = "<string>") -> Report:
    """Run MX901–MX904 over one source blob. A file that does not parse
    yields an empty report (``tracer_lint`` owns the MX200 diagnostic)."""
    report = Report()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return report
    _attach_parents(tree)
    aware = _is_aware(tree)
    _check_collective_flow(tree, filename, report)
    _check_elected_effects(tree, filename, report, aware)
    _check_elastic_world(tree, filename, report)
    _check_rng_divergence(tree, filename, report, aware)
    report.diagnostics.sort(key=lambda d: (d.node or "", d.code))
    return report

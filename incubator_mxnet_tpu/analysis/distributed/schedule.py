"""Collective-schedule extraction + the MX905 HLO-layer pass.

:func:`schedule_of` walks a traced graph's jaxpr in deterministic
(program) order and returns the ordered ``verb@axes`` sequence of its
explicit collective primitives — THE extractor both the static MX905
pass and the runtime :mod:`~incubator_mxnet_tpu.telemetry.
collective_ledger` fingerprint share, so the two surfaces can never
disagree about what "the collective schedule" of a graph is.

MX905 is the cross-bucket projection of the same invariant the ledger
checks cross-process: every executable of one entry point must issue the
same collective verb/axis sequence. Two buckets of one served model (or
a step graph re-traced under a new signature) that lower to *different*
schedules mean the program's collective structure depends on data
geometry — exactly the divergence that, spread across hosts instead of
buckets, wedges the pod.
"""
from __future__ import annotations

from typing import Dict, List

from ..hlo.cost import _COLLECTIVE_VERBS, _collective_axes
from ..hlo.trace import TracedGraph, walk_eqns

__all__ = ["schedule_of", "schedule_str"]


def schedule_of(closed) -> List[str]:
    """Ordered ``verb@axis[,axis...]`` entries for every explicit
    collective primitive in a (closed) jaxpr, sub-jaxprs included, in
    deterministic program order. Loop bodies contribute their schedule
    once — ORDER is the invariant here, not executed counts (the cost
    model owns trip-multiplied accounting)."""
    out: List[str] = []
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    for eqn in walk_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _COLLECTIVE_VERBS:
            axes = ",".join(str(a) for a in _collective_axes(eqn)) or "?"
            out.append(f"{_COLLECTIVE_VERBS[name]}@{axes}")
    return out


def schedule_str(schedule: List[str]) -> str:
    return " -> ".join(schedule) if schedule else "(no collectives)"


def _register() -> None:
    from ..hlo.passes import register_hlo_pass

    @register_hlo_pass("hlo_collective_schedule",
                       describe="collective verb/axis sequence diverges "
                                "across buckets of one entry (static twin "
                                "of the telemetry collective ledger's "
                                "cross-process crosscheck), MX905")
    def hlo_collective_schedule(ctx) -> None:
        by_entry: Dict[tuple, List[TracedGraph]] = {}
        for g in ctx.graphs:
            by_entry.setdefault((g.entry, g.kind), []).append(g)
        for (entry, _kind), graphs in by_entry.items():
            if len(graphs) < 2:
                continue
            schedules: Dict[tuple, List[str]] = {}
            for g in graphs:
                schedules.setdefault(tuple(schedule_of(g.closed)),
                                     []).append(g.site)
            if len(schedules) < 2:
                continue
            sites = "; ".join(
                f"{'+'.join(v)}→[{schedule_str(list(k))}]"
                for k, v in sorted(schedules.items(),
                                   key=lambda kv: kv[1]))
            ctx.diag(
                "MX905",
                f"{len(schedules)} distinct collective schedules across "
                f"{len(graphs)} graphs of one entry [{sites}]: every "
                "executable of an entry must issue the same collective "
                "verb/axis sequence — a geometry-dependent collective "
                "structure is the same divergence that, spread across "
                "hosts, leaves part of the pod blocked in a collective "
                "the rest never issues (the runtime twin is the "
                "telemetry collective ledger's fingerprint crosscheck)",
                node=f"{entry}[{len(schedules)} schedules]",
                severity="error")


_register()

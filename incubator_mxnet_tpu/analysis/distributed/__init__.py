"""``mx.analysis.distributed`` — SPMD divergence passes for the
multi-host tier (the MX9xx family).

Fourth lint registry beside the graph (MX0xx), compiled-graph (MX7xx),
and concurrency (MX8xx) families, aimed at the invariant the
multi-controller JAX model rests on: *every process runs the same
program*. Nothing crashes when the invariant breaks — one host takes a
divergent branch and the rest of the pod blocks in a collective forever
— so the checks must run before the pod does.

==========================  ==============================================
``dist_collective_flow``     MX901 host-conditional control flow enclosing
                             collective issues / jit builds / kv traffic
``dist_elected_effects``     MX902 persistent writes with no host-0
                             election in multi-host-aware modules
``dist_elastic_world``       MX903 world sizes frozen at import time
``dist_rng_divergence``      MX904 unseeded/time-seeded randomness without
                             a process-folded or broadcast seed
``hlo_collective_schedule``  MX905 collective verb/axis sequence diverges
                             across buckets of one entry (HLO layer)
==========================  ==============================================

MX901 and MX902 are each other's inverse: collectives must NOT diverge
across hosts, filesystem effects MUST (one elected writer). MX905 runs
in the ``analysis.hlo`` pass registry over traced graphs; the rest are
source lints. Run them via ``python -m tools.mxlint --distributed``
(defaults to the installed package) or programmatically::

    report = mx.analysis.distributed.lint_paths(["incubator_mxnet_tpu"])

The **runtime twin** is :mod:`incubator_mxnet_tpu.telemetry.
collective_ledger` (re-exported here as ``distributed.ledger``): under
``MXTPU_COLLECTIVE_LEDGER=1`` every pjit step/bucket build banks a
fingerprint of its collective schedule (the same
:func:`~.schedule.schedule_of` extractor MX905 uses, plus comm bytes
from the cost model), and :func:`crosscheck` exchanges the fingerprints
across processes at ``dist.initialize()`` and on any post-warmup
recompile — a mismatch writes one flight bundle and raises loudly
instead of wedging the pod. The exact analogue of MX802↔``lockcheck``
one layer up: static pass finds the hazard in CI, runtime twin catches
the escape in production.

Inline suppressions work as everywhere else: annotate intentional
divergence (``# mxlint: disable=MX902`` on a per-host forensics write)
so the package self-lints clean under ``--strict``.
"""
from __future__ import annotations

from typing import List, Optional

from ..diagnostics import Report, apply_suppressions, walk_lint
from .checks import DIST_PASSES, check_source
from . import schedule  # noqa: F401  (registers hlo_collective_schedule)
from .schedule import schedule_of, schedule_str  # noqa: F401
from ...telemetry import collective_ledger as ledger  # noqa: F401

__all__ = ["lint_source", "lint_file", "lint_paths", "crosscheck",
           "DIST_PASSES", "list_distributed_passes", "schedule_of",
           "schedule_str", "ledger"]


def list_distributed_passes() -> List[str]:
    return list(DIST_PASSES)


def lint_source(src: str, filename: str = "<string>") -> Report:
    """The MX901–MX904 source passes over one blob, inline suppressions
    applied (MX905 needs traced graphs — it runs in the hlo registry)."""
    return apply_suppressions(check_source(src, filename), src)


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_paths(paths) -> Report:
    """The MX9xx source passes over files/directories (the
    ``mxlint --distributed`` entry point)."""
    return walk_lint(paths, lint_file)


def crosscheck(tag: str = "manual", peers=None,
               timeout_s: Optional[float] = None):
    """Exchange this process's banked collective-schedule fingerprints
    with every peer and raise on mismatch — a re-export of
    :func:`telemetry.collective_ledger.crosscheck` at the analysis
    surface (the ``concurrency.crosscheck`` analogue)."""
    return ledger.crosscheck(tag, peers=peers, timeout_s=timeout_s)

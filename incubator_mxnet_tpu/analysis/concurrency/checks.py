"""The MX8xx concurrency checks over a merged package model.

Whole-package by design: MX802's lock-acquisition graph only means
something when every module's ``with``-regions and cross-module calls
land in ONE graph (a deadlock needs two sites that never appear in the
same file). The other four checks are per-class/per-file but share the
same extracted facts and the same inter-procedural refinements:

- **lock-held closure**: a method whose every visible intra-class call
  site sits inside a lock region is analyzed as if its whole body held
  that lock (``CompiledModel._compile`` is only ever called under the
  model lock — flagging its cache write would be a false positive);
- **init-only closure**: a method only reachable from ``__init__`` runs
  before any thread exists (happens-before via ``Thread.start``), so its
  unlocked mutations are construction, not races.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..diagnostics import Diagnostic, Report
from .extract import FileFacts, UnitFacts

__all__ = ["PackageModel", "run_checks", "CONCURRENCY_PASSES"]

#: pass name -> description (rendered into docs/api/analysis.md by
#: tools/gen_docs.py, mirroring PASSES / HLO_PASSES)
CONCURRENCY_PASSES = {
    "conc_shared_state": "MX801 shared-attribute mutation without the "
                         "lock that guards it elsewhere (binding "
                         "inferred from `with self._lock:` dominance), "
                         "in classes that run threads",
    "conc_lock_order": "MX802 lock-order inversion: cycle in the "
                       "whole-package static lock-acquisition graph "
                       "(incl. non-reentrant re-acquisition); the "
                       "static twin of the MXTPU_LOCKCHECK runtime "
                       "sanitizer",
    "conc_blocking_hold": "MX803 blocking call (socket/queue/sleep/"
                          "join/XLA compile) while holding a lock",
    "conc_thread_lifecycle": "MX804 thread hygiene: Thread() without "
                             "name=/daemon=, non-daemon threads never "
                             "joined, start() in __init__ before state "
                             "is fully assigned",
    "conc_cache_sync": "MX805 jit/bucket compile caches (the ones "
                       "telemetry.compile_log tracks) accessed outside "
                       "the owning class's lock",
}

_CACHE_NAME_RE = re.compile(r"^_?(exe|jit_cache|cache|caches)$")


class PackageModel:
    """Merged facts + derived tables for one lint invocation."""

    def __init__(self, files: Sequence[FileFacts]):
        self.files = list(files)
        #: "stem.func" / unit qname -> [UnitFacts]
        self.func_table: Dict[str, List[UnitFacts]] = {}
        #: "Class::method" -> [UnitFacts]
        self.method_table: Dict[str, List[UnitFacts]] = {}
        #: lock id -> kind ("Lock" | "RLock")
        self.lock_kinds: Dict[str, str] = {}
        self.unit_file: Dict[str, FileFacts] = {}
        for ff in self.files:
            self.lock_kinds.update(ff.module_locks)
            for cf in ff.classes.values():
                for attr, kind in cf.lock_attrs.items():
                    self.lock_kinds[f"{cf.name}.{attr}"] = kind
            for qname, unit in ff.units.items():
                self.unit_file[qname] = ff
                self.func_table.setdefault(qname, []).append(unit)
                parts = qname.split(".")
                if len(parts) >= 2:
                    # "stem.func" and "stem.Class.m" both index under
                    # their dotted key; class methods also under ::
                    self.func_table.setdefault(
                        ".".join(parts[-2:]), []).append(unit)
                if unit.cls is not None:
                    self.method_table.setdefault(
                        f"{unit.cls}::{unit.name}", []).append(unit)
        self._trans_acquires: Optional[Dict[str, Set[str]]] = None
        self._trans_blocking: Optional[Dict[str, Set[str]]] = None

    # -- call resolution ------------------------------------------------
    def resolve(self, target: str) -> List[UnitFacts]:
        """One call-target candidate -> unit(s). ``Cls::m`` hits the
        method table; ``Cls::__init__``-style falls back to the class
        constructor when a bare class call was recorded."""
        if "::" in target:
            hits = self.method_table.get(target, [])
            if hits:
                return hits
            return []
        hits = self.func_table.get(target, [])
        if hits:
            return hits
        # a Name call may be a CLASS: route to its __init__
        tail = target.rsplit(".", 1)[-1]
        return self.method_table.get(f"{tail}::__init__", [])

    def resolve_call(self, targets: Tuple[str, ...]) -> List[UnitFacts]:
        out: List[UnitFacts] = []
        for t in targets:
            out.extend(self.resolve(t))
        return out

    # -- fixed points ---------------------------------------------------
    def trans_acquires(self) -> Dict[str, Set[str]]:
        """unit qname -> every lock id the unit may acquire, transitively
        through resolved calls (the reachability MX802's edges need)."""
        if self._trans_acquires is not None:
            return self._trans_acquires
        acq: Dict[str, Set[str]] = {
            q: {r.lock_id for u in us for r in u.regions}
            for q, us in self.func_table.items()}
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for q, us in self.func_table.items():
                cur = acq[q]
                for u in us:
                    for call in u.calls:
                        for callee in self.resolve_call(call.targets):
                            extra = acq.get(callee.qname, set())
                            if not extra <= cur:
                                cur |= extra
                                changed = True
        self._trans_acquires = acq
        return acq

    def trans_blocking(self) -> Dict[str, Set[str]]:
        """unit qname -> blocking-operation kinds reachable from it."""
        if self._trans_blocking is not None:
            return self._trans_blocking
        blk: Dict[str, Set[str]] = {
            q: {b.what for u in us for b in u.blocks}
            for q, us in self.func_table.items()}
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for q, us in self.func_table.items():
                cur = blk[q]
                for u in us:
                    for call in u.calls:
                        for callee in self.resolve_call(call.targets):
                            extra = blk.get(callee.qname, set())
                            if not extra <= cur:
                                cur |= extra
                                changed = True
        self._trans_blocking = blk
        return blk

    # -- per-class closures ---------------------------------------------
    def class_units(self, ff: FileFacts, cname: str) -> List[UnitFacts]:
        prefix = f"{ff.stem}.{cname}."
        return [u for q, u in ff.units.items() if q.startswith(prefix)]

    def _method_call_sites(self, ff: FileFacts, cname: str
                           ) -> Dict[str, List]:
        """method bare name -> [(caller unit, CallSite)] for visible
        intra-class ``self.m()`` calls."""
        sites: Dict[str, List] = {}
        key_prefix = f"{cname}::"
        for u in self.class_units(ff, cname):
            for call in u.calls:
                for t in call.targets:
                    if t.startswith(key_prefix):
                        sites.setdefault(t[len(key_prefix):], []).append(
                            (u, call))
        return sites

    def lock_held_methods(self, ff: FileFacts, cname: str) -> Set[str]:
        """Methods whose every visible intra-class call site holds one of
        the class's locks (computed to a fixed point so helper chains
        under the lock stay covered)."""
        cf = ff.classes[cname]
        sites = self._method_call_sites(ff, cname)
        held: Set[str] = set()
        own = {f"{cname}.{a}" for a in cf.lock_attrs}
        for _ in range(6):
            new = set(held)
            for m in cf.methods:
                ss = sites.get(m)
                if not ss:
                    continue
                if all(bool(set(call.held) & own)
                       or caller.name in held
                       for caller, call in ss):
                    new.add(m)
            if new == held:
                break
            held = new
        return held

    def init_only_methods(self, ff: FileFacts, cname: str) -> Set[str]:
        """Methods only reachable (visibly) from ``__init__`` — their
        unlocked mutations happen before any thread can exist."""
        cf = ff.classes[cname]
        sites = self._method_call_sites(ff, cname)
        init_only: Set[str] = set()
        for _ in range(6):
            new = set(init_only)
            for m in cf.methods:
                if m == "__init__":
                    continue
                ss = sites.get(m)
                if not ss:
                    continue
                if all(caller.name == "__init__"
                       or caller.name in init_only
                       for caller, _call in ss):
                    new.add(m)
            if new == init_only:
                break
            init_only = new
        return init_only


_PASS_OF = {"MX801": "conc_shared_state", "MX802": "conc_lock_order",
            "MX803": "conc_blocking_hold",
            "MX804": "conc_thread_lifecycle", "MX805": "conc_cache_sync"}


def _diag(code: str, msg: str, ff: FileFacts, lineno: int,
          op: Optional[str] = None) -> Diagnostic:
    return Diagnostic(code, msg, node=f"{ff.path}:{lineno}", op=op,
                      pass_name=_PASS_OF[code])


# ---------------------------------------------------------------------------
# MX801 — unlocked mutation of a lock-bound shared attribute
# ---------------------------------------------------------------------------

def _check_shared_state(model: PackageModel, report: Report) -> None:
    for ff in model.files:
        for cname, cf in ff.classes.items():
            if not cf.lock_attrs:
                continue
            units = model.class_units(ff, cname)
            if not any(u.threads for u in units):
                continue  # no threads born here: no cross-thread sharing
            own = {f"{cname}.{a}" for a in cf.lock_attrs}
            lock_held = model.lock_held_methods(ff, cname)
            init_only = model.init_only_methods(ff, cname)
            # binding: attr -> locks it was ever mutated under
            bound: Dict[str, Set[str]] = {}
            for u in units:
                for m in u.muts:
                    if m.kind != "mut":
                        continue
                    guards = set(m.held) & own
                    if guards:
                        bound.setdefault(m.attr, set()).update(guards)
            if not bound:
                continue
            seen: Set[Tuple[str, str]] = set()
            for u in units:
                exempt = (u.name == "__init__" or u.name in init_only
                          or u.name in lock_held)
                if exempt:
                    continue
                for m in u.muts:
                    if m.kind != "mut" or m.attr not in bound:
                        continue
                    if set(m.held) & bound[m.attr]:
                        continue
                    key = (u.qname, m.attr)
                    if key in seen:
                        continue
                    seen.add(key)
                    locks = "/".join(sorted(bound[m.attr]))
                    report.add(_diag(
                        "MX801",
                        f"self.{m.attr} is mutated here without "
                        f"{locks}, but other sites mutate it under that "
                        f"lock — and {cname} runs threads, so both sides "
                        "can interleave",
                        ff, m.lineno, op=f"{cname}.{u.name}"))


# ---------------------------------------------------------------------------
# MX802 — lock-order inversion (cycle in the acquisition graph)
# ---------------------------------------------------------------------------

def _build_edges(model: PackageModel):
    """(src lock, dst lock) -> provenance {file, line, via}."""
    acq = model.trans_acquires()
    edges: Dict[Tuple[str, str], Dict] = {}

    def add(src, dst, ff, line, via):
        if src == dst:
            # same-lock edge: only meaningful for non-reentrant locks,
            # and reported directly (a cycle of length 1)
            if model.lock_kinds.get(src) == "RLock":
                return
        edges.setdefault((src, dst), {
            "file": ff.path, "line": line, "via": via})

    for ff in model.files:
        for u in ff.units.values():
            # lexical with-in-with nesting, recorded by the scanner
            for outer, inner, line in u.nestings:
                add(outer, inner, ff, line, "nested with")
            # calls made while holding: every lock the callee may
            # transitively acquire orders after every held lock
            for call in u.calls:
                if not call.held:
                    continue
                for callee in model.resolve_call(call.targets):
                    for dst in acq.get(callee.qname, ()):
                        for src in call.held:
                            add(src, dst, ff, call.lineno,
                                f"call to {callee.qname}")
    return edges


def _find_cycles(edges: Dict[Tuple[str, str], Dict]) -> List[List[str]]:
    """Simple cycles (as node lists) — Tarjan SCCs, then one witness
    cycle per nontrivial SCC plus explicit self-loops."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on: Set[str] = set()
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph[v]:
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    cycles: List[List[str]] = []
    for comp in sccs:
        if len(comp) > 1:
            cycles.append(sorted(comp))
    for (a, b) in edges:
        if a == b:
            cycles.append([a])
    return cycles


def _check_lock_order(model: PackageModel, report: Report) -> None:
    edges = _build_edges(model)
    cycles = _find_cycles(edges)
    for cyc in cycles:
        if len(cyc) == 1:
            a = cyc[0]
            prov = edges[(a, a)]
            report.add(Diagnostic(
                "MX802",
                f"non-reentrant lock {a} can be re-acquired while "
                f"already held (via {prov['via']}) — certain "
                "self-deadlock on that path",
                node=f"{prov['file']}:{prov['line']}",
                op=a, pass_name="conc_lock_order"))
            continue
        # one finding per cycle, anchored at its first edge's site
        cyc_edges = [(a, b) for (a, b) in edges
                     if a in cyc and b in cyc and a != b]
        detail = "; ".join(
            f"{a}→{b} at {os.path.basename(edges[(a, b)]['file'])}:"
            f"{edges[(a, b)]['line']} ({edges[(a, b)]['via']})"
            for a, b in sorted(cyc_edges)[:6])
        first = edges[sorted(cyc_edges)[0]]
        report.add(Diagnostic(
            "MX802",
            f"lock-order cycle among {{{', '.join(cyc)}}} — threads "
            f"taking these locks in different orders can deadlock; "
            f"edges: {detail}",
            node=f"{first['file']}:{first['line']}",
            op=" -> ".join(cyc), pass_name="conc_lock_order"))


# ---------------------------------------------------------------------------
# MX803 — blocking while holding a lock
# ---------------------------------------------------------------------------

def _check_blocking_hold(model: PackageModel, report: Report) -> None:
    blk = model.trans_blocking()
    for ff in model.files:
        for u in ff.units.values():
            per_region: Dict[int, Set[str]] = {}
            for b in u.blocks:
                if b.held:
                    per_region.setdefault(b.region_line, set()).add(b.what)
            for call in u.calls:
                if not call.held:
                    continue
                for callee in model.resolve_call(call.targets):
                    kinds = blk.get(callee.qname, set())
                    if kinds:
                        per_region.setdefault(
                            call.region_line, set()).update(
                            f"{k} via {callee.qname.rsplit('.', 1)[-1]}()"
                            for k in sorted(kinds)[:3])
            for rline, kinds in sorted(per_region.items()):
                report.add(_diag(
                    "MX803",
                    "blocking operation(s) while holding a lock: "
                    + ", ".join(sorted(kinds)[:4]) +
                    " — every other thread contending for this lock "
                    "stalls behind the slow call",
                    ff, rline, op=u.qname))


# ---------------------------------------------------------------------------
# MX804 — thread lifecycle hygiene
# ---------------------------------------------------------------------------

def _check_thread_lifecycle(model: PackageModel, report: Report) -> None:
    for ff in model.files:
        for u in ff.units.values():
            for tc in u.threads:
                if tc.ctor != "Thread":
                    continue  # Timer's ctor takes neither name nor daemon
                missing = [k for k in ("name", "daemon")
                           if k not in tc.kwargs]
                if missing:
                    report.add(_diag(
                        "MX804",
                        "threading.Thread without explicit "
                        + "/".join(f"{k}=" for k in missing) +
                        " — anonymous threads make hang dumps and the "
                        "lockcheck timeline unreadable, and implicit "
                        "daemon-ness inherits the spawner's by accident",
                        ff, tc.lineno, op=u.qname))
                if tc.daemon_false and not ff.joins_anywhere:
                    report.add(_diag(
                        "MX804",
                        "non-daemon thread is never joined anywhere in "
                        "this file — process shutdown will block on it",
                        ff, tc.lineno, op=u.qname))
            # start() in __init__ before state is fully assigned
            if u.name != "__init__" or u.cls is None:
                continue
            thread_dests = {tc.assigned_to for tc in u.threads
                            if tc.assigned_to}
            cf = ff.classes.get(u.cls)
            if cf:
                thread_dests |= {f"self.{a}" for a, t in
                                 cf.attr_types.items()
                                 if t in ("Thread", "Timer")}
            if not thread_dests:
                continue
            start_lines = [c.lineno for c in u.calls
                           if any(t.endswith("::start") or
                                  t.endswith(".start") for t in c.targets)]
            # also catch `self._thread.start()` / `t.start()` that did
            # not resolve: scan blocks? cheap re-scan via muts is not
            # possible — record from calls with unresolved targets is
            # not kept, so approximate with resolved ones plus the
            # conventional pattern below.
            last_mut = max((m.lineno for m in u.muts if m.kind == "mut"),
                           default=0)
            for sl in start_lines:
                if last_mut > sl:
                    report.add(_diag(
                        "MX804",
                        "thread started inside __init__ before the "
                        "instance finished assigning its state (a "
                        f"mutation follows on line {last_mut}) — the "
                        "thread can observe a half-built object",
                        ff, sl, op=f"{u.cls}.__init__"))


# ---------------------------------------------------------------------------
# MX805 — unsynchronized compile-cache access
# ---------------------------------------------------------------------------

def _check_cache_sync(model: PackageModel, report: Report) -> None:
    for ff in model.files:
        for cname, cf in ff.classes.items():
            if not cf.lock_attrs:
                continue
            units = model.class_units(ff, cname)
            own = {f"{cname}.{a}" for a in cf.lock_attrs}
            # compile-backed cache attrs: subscript-mutated in a unit
            # that also performs a compile-ish op, or canonically named
            cache_attrs: Set[str] = set()
            for u in units:
                if not u.compileish:
                    continue
                for m in u.muts:
                    if m.kind == "mut" and _CACHE_NAME_RE.match(m.attr):
                        cache_attrs.add(m.attr)
            if not cache_attrs:
                continue
            lock_held = model.lock_held_methods(ff, cname)
            init_only = model.init_only_methods(ff, cname)
            seen: Set[Tuple[str, str]] = set()
            for u in units:
                if u.name == "__init__" or u.name in init_only \
                        or u.name in lock_held:
                    continue
                for m in u.muts:
                    if m.attr not in cache_attrs:
                        continue
                    if set(m.held) & own:
                        continue
                    key = (u.qname, m.attr)
                    if key in seen:
                        continue
                    seen.add(key)
                    report.add(_diag(
                        "MX805",
                        f"compile cache self.{m.attr} accessed without "
                        f"{'/'.join(sorted(own))} — a racing thread can "
                        "see a half-installed executable or trigger a "
                        "duplicate XLA compile (exactly what the "
                        "telemetry compile ledger would flag at runtime)",
                        ff, m.lineno, op=f"{cname}.{u.name}"))


def run_checks(files: Sequence[FileFacts]) -> Report:
    """All five MX8xx checks over one merged model."""
    model = PackageModel(files)
    report = Report()
    _check_shared_state(model, report)
    _check_lock_order(model, report)
    _check_blocking_hold(model, report)
    _check_thread_lifecycle(model, report)
    _check_cache_sync(model, report)
    return report

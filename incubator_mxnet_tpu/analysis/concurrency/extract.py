"""AST fact extraction for the MX8xx concurrency passes.

One :class:`FileFacts` per source file, merged into a :class:`PackageModel`
by the checks. Everything here is *syntactic*: no imports of the linted
code ever execute (same contract as the MX2xx tracer lint). The model
captures exactly the facts the five checks need:

- **locks**: ``self._x = threading.Lock()`` / ``RLock`` /
  ``lockcheck.make_lock("...")`` sites, identified as ``Class._attr``
  (instance locks) or ``module._VAR`` (module-level locks) — the same ids
  the runtime sanitizer (:mod:`incubator_mxnet_tpu.lockcheck`) stamps on
  its tracked locks, so static and dynamic graphs cross-check by name;
- **units**: every function-like body (module functions, methods, nested
  defs, the module toplevel) with its lock-acquisition regions, resolved
  calls (and which locks were held lexically at each call), attribute
  mutations/reads, directly-blocking operations, and thread constructions;
- **classes**: lock attributes, attribute constructor types (for
  ``self._x.m()`` resolution), thread-target methods.

Call resolution is deliberately conservative: ``self.m()``, bare local /
module functions, ``alias.f()`` through recorded imports, module-level
singletons (``BUS = EventBus()``), and typed self-attributes. Anything
else stays unresolved — the lock graph under-approximates rather than
inventing edges.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["FileFacts", "UnitFacts", "CallSite", "MutSite", "BlockSite",
           "ThreadCtor", "LockRegion", "extract_file", "extract_source"]

#: constructor callables that create a lock (attr or bare name)
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock",
               "make_lock": "Lock", "make_rlock": "RLock"}

#: attr names whose call is a directly-blocking operation when the
#: receiver matches the guard in _blocking_kind
_SOCKET_OPS = {"accept", "recv", "recv_into", "sendall",
               "create_connection"}
_MUTATORS = {"append", "appendleft", "add", "pop", "popleft", "clear",
             "update", "remove", "discard", "extend", "insert",
             "setdefault", "popitem"}
_COMPILEISH = {"jit", "lower", "compile"}


@dataclass
class LockRegion:
    lock_id: str
    lineno: int


@dataclass
class CallSite:
    #: candidate callee keys ("stem.func", "stem.Class.m", nested qname)
    targets: Tuple[str, ...]
    lineno: int
    held: Tuple[str, ...]          # lock ids held lexically at the call
    region_line: int               # innermost with-lock line (0 = none)


@dataclass
class MutSite:
    attr: str
    lineno: int
    held: Tuple[str, ...]
    kind: str                      # "mut" | "read"


@dataclass
class BlockSite:
    what: str                      # e.g. "time.sleep", "socket.recv"
    lineno: int
    held: Tuple[str, ...]
    region_line: int


@dataclass
class ThreadCtor:
    ctor: str                      # "Thread" | "Timer"
    lineno: int
    kwargs: Set[str]
    daemon_false: bool
    target: Optional[str]          # resolved candidate key or None
    assigned_to: Optional[str]     # local name or "self.<attr>"


@dataclass
class UnitFacts:
    qname: str
    cls: Optional[str]
    name: str                      # bare function/method name
    lineno: int
    regions: List[LockRegion] = field(default_factory=list)
    #: (outer lock id, inner lock id, lineno): lexical with-in-with
    nestings: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    muts: List[MutSite] = field(default_factory=list)
    blocks: List[BlockSite] = field(default_factory=list)
    threads: List[ThreadCtor] = field(default_factory=list)
    #: linenos of jit/lower/compile calls (MX805's compile evidence —
    #: ``jax.jit`` itself is deferred tracing, not a blocking op)
    compileish: List[int] = field(default_factory=list)


@dataclass
class ClassFacts:
    name: str
    lineno: int
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr->kind
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr->Class
    methods: Dict[str, str] = field(default_factory=dict)     # name->qname


@dataclass
class FileFacts:
    path: str
    stem: str
    module_locks: Dict[str, str] = field(default_factory=dict)  # id->kind
    classes: Dict[str, ClassFacts] = field(default_factory=dict)
    units: Dict[str, UnitFacts] = field(default_factory=dict)   # qname->
    #: import alias -> module stem (``_tele`` -> ``events``)
    mod_aliases: Dict[str, str] = field(default_factory=dict)
    #: from-imported bare name -> (module stem, name)
    name_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: module-level singleton -> class name in this file (``BUS`` ->
    #: ``EventBus``)
    singletons: Dict[str, str] = field(default_factory=dict)
    joins_anywhere: bool = False   # any ``.join(`` call in the file


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``make_rlock("...")`` → "Lock"/"RLock"."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    return _LOCK_CTORS.get(name)


def _is_thread_ctor(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading" and f.attr in ("Thread", "Timer"):
        return f.attr
    if isinstance(f, ast.Name) and f.id in ("Thread", "Timer"):
        return f.id
    return None


class _Scanner:
    """Walks one file; produces :class:`FileFacts`."""

    def __init__(self, path: str, tree: ast.Module):
        base = os.path.basename(path)
        stem = os.path.splitext(base)[0]
        if stem == "__init__":  # a package's module identity is its dir
            stem = os.path.basename(os.path.dirname(path)) or stem
        self.facts = FileFacts(path=path, stem=stem)
        self._tree = tree

    # -- imports / module level ----------------------------------------
    def scan(self) -> FileFacts:
        for node in ast.walk(self._tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    stem = a.name.rsplit(".", 1)[-1]
                    self.facts.mod_aliases[a.asname or stem] = stem
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    # ``from ..telemetry import events as _tele`` imports a
                    # MODULE; ``from ..fault.retry import call_with_retry``
                    # imports a NAME. Record both readings — the checks
                    # resolve against what actually exists in the package.
                    self.facts.mod_aliases.setdefault(a.asname or a.name,
                                                      a.name)
                    src = (node.module or "").rsplit(".", 1)[-1]
                    if src:
                        self.facts.name_imports[a.asname or a.name] = \
                            (src, a.name)
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "join":
                # only thread-shaped receivers count: `t.join()` /
                # `self._thread.join()`. `", ".join(...)` (Constant) and
                # `os.path.join(...)` (dotted module) must not satisfy
                # the MX804 unjoined-thread check for the whole file.
                recv = node.func.value
                if isinstance(recv, ast.Name) or (
                        isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    self.facts.joins_anywhere = True
        # module-level locks / singletons / classes / functions
        for node in self._tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                var = node.targets[0].id
                kind = _lock_ctor_kind(node.value)
                if kind:
                    self.facts.module_locks[
                        f"{self.facts.stem}.{var}"] = kind
                elif isinstance(node.value, ast.Call) and isinstance(
                        node.value.func, ast.Name):
                    self.facts.singletons[var] = node.value.func.id
        for node in self._tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node, prefix=self.facts.stem)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_unit(node, cls=None,
                                qname=f"{self.facts.stem}.{node.name}")
        # the module toplevel is a unit too (module-level with-locks)
        top = ast.Module(body=[n for n in self._tree.body
                               if not isinstance(
                                   n, (ast.ClassDef, ast.FunctionDef,
                                       ast.AsyncFunctionDef))],
                         type_ignores=[])
        self._scan_unit_body(top.body, cls=None,
                             qname=f"{self.facts.stem}.<module>",
                             name="<module>", lineno=0)
        return self.facts

    def _scan_class(self, node: ast.ClassDef, prefix: str) -> None:
        cname = node.name
        cf = self.facts.classes.setdefault(
            cname, ClassFacts(name=cname, lineno=node.lineno))
        # pass 1: lock attrs + attr constructor types, wherever assigned
        # (nested ClassDef subtrees excluded — their self is not ours)
        def _walk_own(n):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, ast.ClassDef):
                    continue
                yield child
                yield from _walk_own(child)

        for sub in _walk_own(node):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name) and tgt.value.id == "self":
                        kind = _lock_ctor_kind(sub.value)
                        if kind:
                            cf.lock_attrs[tgt.attr] = kind
                        elif isinstance(sub.value, ast.Call) and isinstance(
                                sub.value.func, ast.Name):
                            cf.attr_types.setdefault(tgt.attr,
                                                     sub.value.func.id)
                        tk = _is_thread_ctor(sub.value) \
                            if isinstance(sub.value, ast.Call) else None
                        if tk:
                            cf.attr_types[tgt.attr] = tk
        # pass 2: methods (incl. nested classes, qualified)
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{cname}.{child.name}"
                cf.methods[child.name] = q
                self._scan_unit(child, cls=cname, qname=q)
            elif isinstance(child, ast.ClassDef):
                self._scan_class(child, prefix=f"{prefix}.{cname}")

    # -- function bodies ------------------------------------------------
    def _scan_unit(self, node, cls: Optional[str], qname: str) -> None:
        self._scan_unit_body(node.body, cls=cls, qname=qname,
                             name=node.name, lineno=node.lineno)

    def _scan_unit_body(self, body, cls, qname, name, lineno) -> None:
        unit = UnitFacts(qname=qname, cls=cls, name=name, lineno=lineno)
        self.facts.units[qname] = unit
        nested: Dict[str, str] = {}
        for stmt in body:
            self._visit(stmt, unit, held=(), region_line=0, nested=nested)

    def _lock_id(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" and cls:
            cf = self.facts.classes.get(cls)
            if cf and expr.attr in cf.lock_attrs:
                return f"{cls}.{expr.attr}"
        if isinstance(expr, ast.Name):
            mid = f"{self.facts.stem}.{expr.id}"
            if mid in self.facts.module_locks:
                return mid
        return None

    def _visit(self, node, unit: UnitFacts, held, region_line, nested):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            q = f"{unit.qname}.{node.name}"
            nested[node.name] = q
            # nested def bodies run at CALL time: scan as their own unit
            # with an empty held stack (the caller's held locks apply at
            # the call site via trans-acquire propagation)
            self._scan_unit(node, cls=unit.cls, qname=q)
            return
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            rline = region_line
            for item in node.items:
                lid = self._lock_id(item.context_expr, unit.cls)
                if lid is not None:
                    unit.regions.append(LockRegion(lid, node.lineno))
                    for outer in new_held:
                        unit.nestings.append((outer, lid, node.lineno))
                    new_held.append(lid)
                    rline = node.lineno
                else:
                    # `with SomeClass(...):` — model __enter__/__exit__
                    # as calls so a CM that takes locks contributes edges
                    if isinstance(item.context_expr, ast.Call):
                        self._visit(item.context_expr, unit, held,
                                    region_line, nested)
                        tgts = self._call_targets(item.context_expr,
                                                  unit, nested)
                        for suffix in ("__enter__", "__exit__"):
                            cand = tuple(f"{t}.{suffix}" for t in tgts
                                         if t)
                            if cand:
                                unit.calls.append(CallSite(
                                    cand, node.lineno, tuple(held),
                                    region_line))
            for stmt in node.body:
                self._visit(stmt, unit, tuple(new_held), rline, nested)
            return
        if isinstance(node, ast.Call):
            self._record_call(node, unit, held, region_line, nested)
            for child in ast.iter_child_nodes(node):
                self._visit(child, unit, held, region_line, nested)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else \
                [node.target]
            for tgt in targets:
                self._record_mut_target(tgt, unit, held)
            # thread ctor assigned to a name (for MX804 join tracking):
            # the ctor is recorded when the Call node is visited below,
            # so stash the assignment target for it to pick up
            value = getattr(node, "value", None)
            if isinstance(value, ast.Call) and _is_thread_ctor(value):
                tgt0 = targets[0]
                dest = None
                if isinstance(tgt0, ast.Name):
                    dest = tgt0.id
                elif isinstance(tgt0, ast.Attribute) and isinstance(
                        tgt0.value, ast.Name) and tgt0.value.id == "self":
                    dest = f"self.{tgt0.attr}"
                self._pending_thread_dest = dest
            for child in ast.iter_child_nodes(node):
                self._visit(child, unit, held, region_line, nested)
            self._pending_thread_dest = None
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                self._record_mut_target(base, unit, held)
            return
        if isinstance(node, ast.Lambda):
            return  # runs at call time; attributing its body here would
            # invent lock context the lambda never executes under
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load):
            unit.muts.append(MutSite(node.attr, node.lineno, tuple(held),
                                     "read"))
        for child in ast.iter_child_nodes(node):
            self._visit(child, unit, held, region_line, nested)

    def _record_mut_target(self, tgt, unit, held) -> None:
        base = tgt
        if isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name) and base.value.id == "self":
            unit.muts.append(MutSite(base.attr, base.lineno, tuple(held),
                                     "mut"))
        elif isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                self._record_mut_target(el, unit, held)

    # -- call handling --------------------------------------------------
    def _call_targets(self, call: ast.Call, unit: UnitFacts,
                      nested: Dict[str, str]) -> Tuple[str, ...]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in nested:
                return (nested[f.id],)
            if f.id in self.facts.name_imports:
                src, name = self.facts.name_imports[f.id]
                return (f"{src}.{name}",)
            return (f"{self.facts.stem}.{f.id}",)
        if isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name):
                if recv.id == "self" and unit.cls:
                    return (f"{unit.cls}::{f.attr}",)
                if recv.id in self.facts.singletons:
                    return (f"{self.facts.singletons[recv.id]}::{f.attr}",)
                if recv.id in self.facts.mod_aliases:
                    stem = self.facts.mod_aliases[recv.id].rsplit(
                        ".", 1)[-1]
                    return (f"{stem}.{f.attr}",)
            elif isinstance(recv, ast.Attribute) and isinstance(
                    recv.value, ast.Name) and recv.value.id == "self" \
                    and unit.cls:
                cf = self.facts.classes.get(unit.cls)
                t = cf.attr_types.get(recv.attr) if cf else None
                if t:
                    return (f"{t}::{f.attr}",)
        return ()

    def _blocking_kind(self, call: ast.Call, unit: UnitFacts
                       ) -> Optional[str]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        recv = f.value
        recv_name = recv.id if isinstance(recv, ast.Name) else None
        if f.attr == "sleep" and recv_name == "time":
            return "time.sleep"
        if f.attr in _SOCKET_OPS and recv_name != "self":
            return f"socket.{f.attr}"
        if f.attr in ("lower", "compile") and recv_name != "re":
            return f"xla.{f.attr}"
        # join/wait/get/put only on receivers we can type as
        # Thread/Event/Queue (string.join / dict.get must not fire)
        typed = None
        if isinstance(recv, ast.Attribute) and isinstance(
                recv.value, ast.Name) and recv.value.id == "self" \
                and unit.cls:
            cf = self.facts.classes.get(unit.cls)
            typed = cf.attr_types.get(recv.attr) if cf else None
        if f.attr == "join" and typed in ("Thread", "Timer"):
            return "Thread.join"
        if f.attr == "wait" and typed in ("Event", "Condition"):
            return "Event.wait"
        if f.attr in ("get", "put") and typed == "Queue":
            for kw in call.keywords:
                if kw.arg == "block" and isinstance(
                        kw.value, ast.Constant) and kw.value.value is False:
                    return None
            return f"Queue.{f.attr}"
        return None

    _pending_thread_dest: Optional[str] = None

    def _record_call(self, call: ast.Call, unit: UnitFacts, held,
                     region_line, nested) -> None:
        ctor = _is_thread_ctor(call)
        if ctor:
            kwargs = {kw.arg for kw in call.keywords if kw.arg}
            daemon_false = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in call.keywords)
            target = None
            tnode = next((kw.value for kw in call.keywords
                          if kw.arg == "target"), None)
            if tnode is None and ctor == "Timer" and len(call.args) >= 2:
                tnode = call.args[1]
            if isinstance(tnode, ast.Attribute) and isinstance(
                    tnode.value, ast.Name) and tnode.value.id == "self" \
                    and unit.cls:
                target = f"{unit.cls}::{tnode.attr}"
            elif isinstance(tnode, ast.Name):
                target = nested.get(tnode.id,
                                    f"{self.facts.stem}.{tnode.id}")
            unit.threads.append(ThreadCtor(
                ctor, call.lineno, kwargs, daemon_false, target,
                self._pending_thread_dest))
        tgts = self._call_targets(call, unit, nested)
        if tgts:
            unit.calls.append(CallSite(tgts, call.lineno, tuple(held),
                                       region_line))
        blk = self._blocking_kind(call, unit)
        if blk:
            unit.blocks.append(BlockSite(blk, call.lineno, tuple(held),
                                         region_line))
        f = call.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            recv_name = recv.id if isinstance(recv, ast.Name) else None
            if f.attr in _COMPILEISH and recv_name != "re":
                unit.compileish.append(call.lineno)
            # mutator-method calls are attribute mutations too
            # (self._queue.append(x), self._conns.discard(c), ...)
            if f.attr in _MUTATORS and isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                unit.muts.append(MutSite(recv.attr, call.lineno,
                                         tuple(held), "mut"))


def extract_source(src: str, path: str = "<string>") -> Optional[FileFacts]:
    """Parse + scan one source blob; None when it does not parse (the
    tracer lint owns the MX200 diagnostic)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return None
    return _Scanner(path, tree).scan()


def extract_file(path: str) -> Optional[FileFacts]:
    with open(path) as f:
        return extract_source(f.read(), path)

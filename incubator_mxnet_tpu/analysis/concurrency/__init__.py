"""``mx.analysis.concurrency`` — race/deadlock passes for the threaded
runtime tier (the MX8xx family).

Third pass registry beside the MX0xx graph passes and the MX7xx
compiled-graph passes, aimed at the package's *own* threading layer
(DynamicBatcher, the serve TCP front end, AsyncKVStore/AsyncPSServer,
the telemetry bus, watchdog, chaos injector — ~100 ``threading`` sites):
PyGraph's argument (PAPERS.md) applied to locks instead of graphs — move
the failure detection from "the deadlock you hit in production" into a
static check that runs in CI.

=====================  ===================================================
``conc_shared_state``   MX801 unlocked mutation of a lock-bound attribute
``conc_lock_order``     MX802 lock-order inversion (whole-package
                        acquisition-graph cycle)
``conc_blocking_hold``  MX803 blocking call while holding a lock
``conc_thread_lifecycle`` MX804 Thread hygiene (name=/daemon=/join/
                        start-in-``__init__``)
``conc_cache_sync``     MX805 unsynchronized jit/bucket compile caches
=====================  ===================================================

Unlike the per-file AST lints, MX802 is *whole-package*: every file's
``with``-regions and cross-module calls merge into one lock-acquisition
graph before cycle detection (a deadlock needs two sites that never share
a file). Run it via ``python -m tools.mxlint --concurrency`` (defaults to
the installed package) or programmatically::

    report = mx.analysis.concurrency.lint_paths(["incubator_mxnet_tpu"])

The **dynamic twin** is :mod:`incubator_mxnet_tpu.lockcheck` (re-exported
here as ``concurrency.lockcheck``): under ``MXTPU_LOCKCHECK=1`` every
lock created through ``lockcheck.make_lock`` records real acquisition
order, flags inversions as ``concurrency.inversion`` telemetry events,
and bounds inverted acquires so a genuine deadlock fails instead of
hanging. :func:`crosscheck` joins the two graphs by lock name: runtime
edges the static pass never derived are its blind spots; static cycle
edges observed live corroborate an MX802 finding.

Inline suppressions work as everywhere else: annotate intentional sites
(``# mxlint: disable=MX803`` on the flagged ``with`` line) so the package
self-lints clean under ``--strict``.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..diagnostics import Report, apply_suppressions
from .checks import CONCURRENCY_PASSES, PackageModel, run_checks
from .extract import FileFacts, extract_file, extract_source
from ... import lockcheck  # noqa: F401  (the runtime sanitizer twin)

__all__ = ["lint_source", "lint_file", "lint_paths", "static_lock_graph",
           "crosscheck", "CONCURRENCY_PASSES", "lockcheck",
           "list_concurrency_passes"]


def list_concurrency_passes() -> List[str]:
    return list(CONCURRENCY_PASSES)


def _collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, files in os.walk(p):
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        out.append(os.path.join(dirpath, fname))
        else:
            out.append(p)
    return out


def _apply_file_suppressions(report: Report,
                             sources: Dict[str, str]) -> Report:
    """Apply each file's inline ``# mxlint: disable=`` markers to the
    findings anchored in it (the merged whole-package report spans many
    files, so suppression is applied per provenance file)."""
    by_file: Dict[str, List] = {}
    for d in report.diagnostics:
        node = d.node or ""
        path = node.rsplit(":", 1)[0] if ":" in node else node
        by_file.setdefault(path, []).append(d)
    kept = Report(skipped=list(report.skipped))
    for path, diags in by_file.items():
        sub = Report(diagnostics=diags)
        src = sources.get(path)
        kept.extend(apply_suppressions(sub, src) if src else sub)
    kept.diagnostics.sort(key=lambda d: (d.node or "", d.code))
    return kept


def lint_paths(paths: Sequence[str]) -> Report:
    """The MX8xx passes over files/directories as ONE merged model (the
    ``mxlint --concurrency`` entry point)."""
    sources: Dict[str, str] = {}
    facts: List[FileFacts] = []
    for path in _collect_files(paths):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        ff = extract_source(src, path)
        if ff is not None:
            sources[path] = src
            facts.append(ff)
    return _apply_file_suppressions(run_checks(facts), sources)


def lint_source(src: str, filename: str = "<string>") -> Report:
    """Single-blob variant (fixtures, tests): the file is its own
    package model, so MX802 sees only its own lock graph."""
    ff = extract_source(src, filename)
    if ff is None:
        return Report()  # tracer_lint owns the MX200 parse diagnostic
    report = run_checks([ff])
    return _apply_file_suppressions(report, {filename: src})


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


# ---------------------------------------------------------------------------
# static graph export + runtime cross-check
# ---------------------------------------------------------------------------

def static_lock_graph(paths: Sequence[str]) -> Dict[Tuple[str, str], Dict]:
    """The MX802 acquisition graph as ``{(src, dst): provenance}`` —
    lock ids match the names runtime ``lockcheck`` locks carry."""
    from .checks import _build_edges
    facts = [ff for ff in (extract_file(p)
                           for p in _collect_files(paths))
             if ff is not None]
    return _build_edges(PackageModel(facts))


def crosscheck(paths: Optional[Sequence[str]] = None,
               runtime_edges: Optional[List[Dict]] = None) -> Dict:
    """Join the static MX802 graph with the runtime sanitizer's observed
    edges (``lockcheck.edges()``) by lock name.

    Returns ``{"confirmed": [...], "static_only": [...],
    "runtime_only": [...], "inversions": [...]}`` — ``runtime_only``
    edges are static blind spots (calls the resolver could not follow);
    ``confirmed`` inversion pairs corroborate an MX802 finding with a
    live observation.
    """
    if paths is None:
        pkg = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        paths = [pkg]
    static = set(static_lock_graph(paths))
    runtime = {(e["held"], e["acquired"])
               for e in (runtime_edges if runtime_edges is not None
                         else lockcheck.edges())}
    inv = lockcheck.inversions()
    return {
        "confirmed": sorted(static & runtime),
        "static_only": sorted(static - runtime),
        "runtime_only": sorted(runtime - static),
        "inversions": inv,
        "confirmed_inversions": sorted(
            {(d["held"], d["acquiring"]) for d in inv
             if (d["held"], d["acquiring"]) in static
             or (d["acquiring"], d["held"]) in static}),
    }

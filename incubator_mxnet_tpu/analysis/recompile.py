"""Recompilation-hazard detector (pass 3, runtime half).

Reference counterpart: ``CachedOp`` keeps ONE captured graph per
(static-shape, train-mode) bucket and MXNet profiled cache misses through
the engine; here every distinct jit signature is a fresh XLA compile —
seconds of latency and growing device memory, invisible without tooling
("Operator Fusion in XLA", PAPERS.md §recompilation). The hybridize cache
(``gluon/block.py _call_cached_op``) calls :func:`note_compile` on every
cache miss; past :data:`RECOMPILE_WARN_THRESHOLD` distinct signatures a
``RecompileWarning`` fires once per block, and :func:`cache_report` turns
the live cache state of a block tree into MX201 diagnostics.

Typical causes the warning points at: unhashable/varying static leaves in
the call args (Python floats that change per step, freshly-built lists),
shape-churning inputs (unbucketed variable-length batches), or toggling
``autograd.record`` patterns that alternate train/eval signatures.
"""
from __future__ import annotations

import os
import warnings
from typing import List

from .diagnostics import Diagnostic, Report

__all__ = ["RecompileWarning", "note_compile", "cache_report",
           "RECOMPILE_WARN_THRESHOLD"]

#: distinct jit signatures per block before warning (env override)
RECOMPILE_WARN_THRESHOLD = int(os.environ.get("MXTPU_RECOMPILE_WARN", "8"))


class RecompileWarning(UserWarning):
    """A hybridized block has compiled many distinct signatures."""


def note_compile(block, signature) -> None:
    """Record one compile signature on ``block`` — the (static cache key,
    input shapes/dtypes) pair, since jax.jit re-traces per aval inside one
    cache entry. Dedupes; warns once when the distinct count crosses the
    threshold. Called by the CachedOp path on every compiled call, so the
    steady-state cost is one set lookup (``signature`` must be hashable)."""
    seen = block.__dict__.setdefault("_compile_sigs", set())
    if signature in seen:
        return
    seen.add(signature)
    block.__dict__.setdefault("_compile_log", []).append(signature)
    n = len(seen)
    # process-wide recompile ledger (mx.telemetry.compile_log): the
    # hybridize cache reports next to CompiledModel and ShardedTrainer,
    # so one table answers "what compiled, when, and was it expected" —
    # mark_warmed("gluon.hybridize") after a warmup loop makes later
    # signatures count as unexpected
    from ..telemetry import compile_log as _compile_log
    _compile_log.note("gluon.hybridize",
                      (type(block).__name__, signature))
    if n == RECOMPILE_WARN_THRESHOLD and \
            not block.__dict__.get("_recompile_warned"):
        block._recompile_warned = True
        warnings.warn(
            f"[MX201] {type(block).__name__}({block.name}): {n} distinct "
            f"jit compile signatures and counting — every new static-arg "
            "value or input shape recompiles. Stabilize static kwargs and "
            "bucket input shapes (mx.analysis.recompile.cache_report(block) "
            "shows the signatures).", RecompileWarning, stacklevel=3)


def _blocks(block):
    yield block
    for child in getattr(block, "_children", {}).values():
        yield from _blocks(child)


def cache_report(block, threshold: int = None) -> Report:
    """MX201 diagnostics for every block in the tree whose live jit cache
    holds more than ``threshold`` distinct signatures (default: the warn
    threshold). Severity is ``warning``: many signatures are a perf hazard,
    not a correctness error."""
    limit = RECOMPILE_WARN_THRESHOLD if threshold is None else threshold
    report = Report()
    for b in _blocks(block):
        # note_compile() runs on every compiled call, so _compile_log is
        # authoritative; a block without one has compiled nothing
        log = b.__dict__.get("_compile_log") or []
        # >= so the block that just tripped the note_compile warning (which
        # points users here) is visible at exactly the threshold
        if len(log) < limit:
            continue
        sigs: List[str] = [repr(k)[:120] for k in log]
        report.add(Diagnostic(
            "MX201",
            f"{len(log)} distinct jit compile signatures (threshold "
            f"{limit}); recent: {sigs[-3:]}",
            node=getattr(b, "name", type(b).__name__),
            op=type(b).__name__, pass_name="recompile",
            severity="warning"))
    return report

"""Observability-hygiene linter (the MX6xx family).

Companion to :mod:`.fault_lint` (protects the run from the machine) and
:mod:`.serve_lint` (protects the request path from the jit cache): this
pass protects the *operator* from flying blind. Hand-rolled
``time.time()`` deltas and ad-hoc counters inside a training loop or a
serving entry point are observability that exists in exactly one
``print`` statement — invisible to the unified event bus, the Prometheus
scrape, and ``telemetry.snapshot()``. One pure-AST check, warning
severity (hygiene, not correctness; ``mxlint --strict`` gates):

- **MX601** — a wall-clock sampling call (``time.time()`` /
  ``time.perf_counter()`` / ``time.monotonic()``) inside a training loop
  (a ``for``/``while`` whose body calls ``.step(...)``) or inside a
  serving entry point (a function named ``predict``/``serve``/``infer``/
  ``handle``/``handle_request``), in a file that shows NO telemetry
  evidence at all. Route the measurement through ``mx.telemetry``
  (``emit`` / ``Histogram`` / ``step_scope``) or ``mx.profiler`` spans
  instead — then it lands in every sink for free.

Heuristics are tuned for zero noise elsewhere: any use of ``telemetry``,
``profiler`` scopes, ``emit``, a metrics instrument, or ``ServeMetrics``
anywhere in the file counts as evidence and silences the pass — code
already on the spine (including the serve/bench internals that IMPLEMENT
the spine) lints clean.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .diagnostics import Diagnostic, Report, walk_lint

__all__ = ["lint_source", "lint_file", "lint_paths"]

#: function/method names treated as request-serving entry points (shared
#: vocabulary with serve_lint MX502)
_ENTRY_NAMES = {"predict", "serve", "infer", "inference", "handle",
                "handle_request"}

#: wall-clock sampling callables (attribute leaf or bare name)
_CLOCK_NAMES = {"time", "perf_counter", "monotonic", "process_time"}

#: any of these identifiers anywhere in the file = the code already
#: publishes into the telemetry spine — MX601 stays quiet
_TELEMETRY_EVIDENCE = {"telemetry", "emit", "step_scope", "request_scope",
                       "Histogram", "Counter", "Gauge", "profiler",
                       "Scope", "Task", "Marker", "ServeMetrics",
                       "record_request", "record_batch", "snapshot"}


def _is_clock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        # time.time() / time.perf_counter(): receiver must be `time`-ish
        # so .time() methods on arbitrary objects don't fire
        recv = f.value
        return f.attr in _CLOCK_NAMES and isinstance(recv, ast.Name) \
            and recv.id == "time"
    if isinstance(f, ast.Name):
        return f.id in {"perf_counter", "monotonic"}
    return False


def _has_telemetry_evidence(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in _TELEMETRY_EVIDENCE:
            return True
        if isinstance(node, ast.Attribute) \
                and node.attr in _TELEMETRY_EVIDENCE:
            return True
    return False


def _step_loops(tree: ast.Module) -> List[ast.AST]:
    loops = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for inner in ast.walk(node):
            if isinstance(inner, ast.Call) \
                    and isinstance(inner.func, ast.Attribute) \
                    and inner.func.attr == "step":
                loops.append(node)
                break
    return loops


def _entry_functions(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in _ENTRY_NAMES]


def lint_source(src: str, filename: str = "<string>") -> Report:
    """Lint one Python source blob for MX6xx findings."""
    report = Report()
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError:
        return report  # tracer_lint owns the MX200 parse diagnostic
    if _has_telemetry_evidence(tree):
        return report
    seen_clocks: Set[int] = set()  # one finding per scope; a clock call
    for where, scopes in (("training loop", _step_loops(tree)),  # inside
                          ("serving entry point",  # nested scopes reports
                           _entry_functions(tree))):  # at the outermost
        for scope in scopes:
            clocks = [n for n in ast.walk(scope)
                      if _is_clock_call(n) and id(n) not in seen_clocks]
            if not clocks:
                continue
            seen_clocks.update(id(n) for n in clocks)
            name = getattr(scope, "name", None)
            report.add(Diagnostic(
                "MX601",
                f"ad-hoc wall-clock timing inside a {where} "
                f"({len(clocks)} clock call(s)) — this measurement is "
                "invisible to the event bus, the Prometheus scrape, and "
                "telemetry.snapshot(); emit it through mx.telemetry "
                "(emit()/Histogram/step_scope) or an mx.profiler span "
                "instead",
                node=f"{filename}:{getattr(clocks[0], 'lineno', 0)}",
                op=name or where, pass_name="telemetry_lint",
                severity="warning"))
    return report


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_paths(paths) -> Report:
    """Lint files and directories (recursing into ``*.py``)."""
    return walk_lint(paths, lint_file)

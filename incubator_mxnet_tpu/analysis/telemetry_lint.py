"""Observability-hygiene linter (the MX6xx family).

Companion to :mod:`.fault_lint` (protects the run from the machine) and
:mod:`.serve_lint` (protects the request path from the jit cache): this
pass protects the *operator* from flying blind. Hand-rolled
``time.time()`` deltas and ad-hoc counters inside a training loop or a
serving entry point are observability that exists in exactly one
``print`` statement — invisible to the unified event bus, the Prometheus
scrape, and ``telemetry.snapshot()``. One pure-AST check, warning
severity (hygiene, not correctness; ``mxlint --strict`` gates):

- **MX601** — a wall-clock sampling call (``time.time()`` /
  ``time.perf_counter()`` / ``time.monotonic()``) inside a training loop
  (a ``for``/``while`` whose body calls ``.step(...)``) or inside a
  serving entry point (a function named ``predict``/``serve``/``infer``/
  ``handle``/``handle_request``), in a file that shows NO telemetry
  evidence at all. Route the measurement through ``mx.telemetry``
  (``emit`` / ``Histogram`` / ``step_scope``) or ``mx.profiler`` spans
  instead — then it lands in every sink for free.
- **MX602** — an ``emit(...)`` bus call inside a *request-path* function
  (``submit``/``call``/``call_detailed``/``predict``/``_flush``/
  ``handle*``/...) with no correlation whatsoever: the call neither
  passes ``request_id=``/``step=`` nor sits lexically inside a
  correlation ``with`` block (``request_scope``/``step_scope``/
  ``trace.span``/``trace.use``). Such an event lands on the timeline as
  a free-floating fact that can never be stitched into any request or
  step story — the uncorrelated telemetry this PR's tracing layer
  exists to eliminate.
- **MX604** — a **stray device sync inside a step loop**: a
  ``.block_until_ready()`` / ``.item()`` call or ``float(...)``
  coercion on a name bound to a ``.step(...)`` result, executed every
  iteration. The guarded trainer already syncs loss/grad-norm in ONE
  device read per step (the fused step's single-sync cadence); a
  per-iteration extra sync re-serializes the host with the device —
  over a tunneled chip each costs ~1-2 ms of pure dispatch latency
  (BASELINE.md). Reads decimated behind an ``if step % N`` cadence (or
  performed once after the loop) pass; ``.asnumpy()`` is exempt as the
  documented honest sync.
- **MX603** — tensor statistics routed through a **host callback inside
  a jitted function**: a ``jax.debug.callback`` / ``jax.debug.print`` /
  ``jax.pure_callback`` / ``io_callback`` call whose arguments carry a
  reduction (``.mean()``, ``jnp.min``, ``linalg.norm``, ...) lexically
  inside a function that is jit-compiled (decorated with
  ``jit``/``jax.jit``/``pjit``, or passed by name to ``jax.jit(...)``
  in the same file). This is the anti-pattern the in-graph numerics
  design forbids: a per-step host callback breaks whole-step capture
  (MX701/MX708 catch it at the HLO level; this is the AST-level twin
  that fires before anything is traced). Return the stats as extra
  pinned outputs and decimate host-side — ``telemetry.numerics`` is
  exactly that machinery.

Heuristics are tuned for zero noise elsewhere: for MX601, any use of
``telemetry``, ``profiler`` scopes, ``emit``, a metrics instrument, or
``ServeMetrics`` anywhere in the file counts as evidence and silences
the pass — code already on the spine (including the serve/bench
internals that IMPLEMENT the spine) lints clean. MX602 is the opposite
polarity (``emit`` IS its subject), so it runs regardless of file-level
evidence; lifecycle emits outside request-path functions (health
transitions, drain, load outcomes) are legitimately uncorrelated and
out of its vocabulary by construction.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .diagnostics import Diagnostic, Report, walk_lint

__all__ = ["lint_source", "lint_file", "lint_paths"]

#: function/method names treated as request-serving entry points (shared
#: vocabulary with serve_lint MX502)
_ENTRY_NAMES = {"predict", "serve", "infer", "inference", "handle",
                "handle_request"}

#: wall-clock sampling callables (attribute leaf or bare name)
_CLOCK_NAMES = {"time", "perf_counter", "monotonic", "process_time"}

#: any of these identifiers anywhere in the file = the code already
#: publishes into the telemetry spine — MX601 stays quiet
_TELEMETRY_EVIDENCE = {"telemetry", "emit", "step_scope", "request_scope",
                       "Histogram", "Counter", "Gauge", "profiler",
                       "Scope", "Task", "Marker", "ServeMetrics",
                       "record_request", "record_batch", "snapshot"}


def _is_clock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        # time.time() / time.perf_counter(): receiver must be `time`-ish
        # so .time() methods on arbitrary objects don't fire
        recv = f.value
        return f.attr in _CLOCK_NAMES and isinstance(recv, ast.Name) \
            and recv.id == "time"
    if isinstance(f, ast.Name):
        return f.id in {"perf_counter", "monotonic"}
    return False


def _has_telemetry_evidence(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in _TELEMETRY_EVIDENCE:
            return True
        if isinstance(node, ast.Attribute) \
                and node.attr in _TELEMETRY_EVIDENCE:
            return True
    return False


def _step_loops(tree: ast.Module) -> List[ast.AST]:
    loops = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for inner in ast.walk(node):
            if isinstance(inner, ast.Call) \
                    and isinstance(inner.func, ast.Attribute) \
                    and inner.func.attr == "step":
                loops.append(node)
                break
    return loops


def _entry_functions(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in _ENTRY_NAMES]


# -- MX602: uncorrelated telemetry on the request path -----------------------

#: functions that handle one request/step — the paths where an
#: uncorrelated event is a stitching failure, not a lifecycle fact
_REQUEST_PATH_NAMES = {"submit", "call", "call_detailed", "predict",
                       "infer", "inference", "serve", "_flush",
                       "_predict", "handle", "handle_request"}
_REQUEST_PATH_PREFIXES = ("handle_", "_handle")

#: with-context callables that establish correlation for everything
#: lexically inside them
_CORRELATION_CTX = {"request_scope", "step_scope", "span", "use",
                    "watch"}

#: emit kwargs that correlate the single event explicitly
_CORRELATION_KWARGS = {"request_id", "step"}


def _is_request_path(name: str) -> bool:
    return name in _REQUEST_PATH_NAMES \
        or name.startswith(_REQUEST_PATH_PREFIXES)


def _is_emit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    leaf = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return leaf == "emit"


def _correlation_withs(func: ast.AST) -> List[ast.With]:
    out = []
    for node in ast.walk(func):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            if not isinstance(expr, ast.Call):
                continue
            f = expr.func
            leaf = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if leaf in _CORRELATION_CTX:
                out.append(node)
                break
    return out


def _inside(node: ast.AST, blocks: List[ast.With]) -> bool:
    """Lexical containment by line span (ast has no parent links; the
    end_lineno span is exact for our purpose)."""
    line = getattr(node, "lineno", None)
    if line is None:
        return False
    for blk in blocks:
        if blk.lineno <= line <= (getattr(blk, "end_lineno", blk.lineno)):
            return True
    return False


def _lint_uncorrelated(tree: ast.Module, filename: str,
                       report: Report) -> None:
    """MX602 over every request-path function in the module."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and _is_request_path(n.name)]
    # drop request-path functions nested inside another collected one:
    # ast.walk(outer) already reaches the inner's emits, so keeping both
    # would report the same call twice under two op= names
    spans = [(f.lineno, getattr(f, "end_lineno", f.lineno)) for f in funcs]
    funcs = [f for i, f in enumerate(funcs)
             if not any(j != i and lo < f.lineno <= hi
                        for j, (lo, hi) in enumerate(spans))]
    for func in funcs:
        blocks = _correlation_withs(func)
        for node in ast.walk(func):
            if not _is_emit_call(node):
                continue
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            if kwargs & _CORRELATION_KWARGS:
                continue
            if _inside(node, blocks):
                continue
            kind = ""
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                kind = f" ({node.args[0].value!r})"
            report.add(Diagnostic(
                "MX602",
                f"bus event{kind} emitted on the request path "
                f"({func.name}()) outside any correlation scope — pass "
                "request_id=/step=, or wrap the path in "
                "telemetry.request_scope()/step_scope()/trace.span() so "
                "the event stitches into a request or step story",
                node=f"{filename}:{getattr(node, 'lineno', 0)}",
                op=func.name, pass_name="telemetry_lint",
                severity="warning"))


# -- MX604: stray device syncs inside step loops -----------------------------

#: method leaves that force a host<->device sync when called on a device
#: array. ``.asnumpy()`` is deliberately NOT here: it is the documented
#: honest sync (BASELINE.md: over a tunneled backend block_until_ready
#: does not even wait for execution), and the sanctioned loop shape
#: syncs it once after the loop or on a decimated cadence.
_SYNC_METHOD_LEAVES = {"block_until_ready", "item"}


def _step_result_names(loop: ast.AST) -> Set[str]:
    """Names bound (anywhere in the loop body) to a ``.step(...)`` call
    result — the device arrays whose every-iteration sync is the smell."""
    out: Set[str] = set()
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr == "step":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _decimated_ifs(loop: ast.AST) -> List[ast.AST]:
    """``if``-blocks whose test contains a modulo — the decimated-cadence
    idiom (``if step % N == 0:``) that keeps a sync OFF the every-step
    path; syncs inside one respect the single-sync cadence and pass."""
    out: List[ast.AST] = []
    for node in ast.walk(loop):
        if isinstance(node, ast.If):
            for t in ast.walk(node.test):
                if isinstance(t, ast.BinOp) and isinstance(t.op, ast.Mod):
                    out.append(node)
                    break
    return out


def _lint_stray_syncs(tree: ast.Module, filename: str,
                      report: Report) -> None:
    """MX604 over every step loop: a ``.block_until_ready()``/``.item()``
    call — or a ``float(...)`` coercion — on a name bound to a
    ``.step(...)`` result, executed every iteration, is a second device
    round trip per step outside the guard's single-sync cadence."""
    seen: Set[int] = set()
    for loop in _step_loops(tree):
        names = _step_result_names(loop)
        if not names:
            continue
        decimated = _decimated_ifs(loop)
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            f = node.func
            hit = None
            if isinstance(f, ast.Attribute) \
                    and f.attr in _SYNC_METHOD_LEAVES \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in names:
                hit = f"{f.value.id}.{f.attr}()"
            elif isinstance(f, ast.Name) and f.id == "float" and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in names:
                hit = f"float({node.args[0].id})"
            if hit is None:
                continue
            if _inside(node, decimated):
                continue   # decimated (if step % N) — cadence respected
            seen.add(id(node))
            report.add(Diagnostic(
                "MX604",
                f"stray device sync {hit} inside a step loop — every "
                "iteration pays a second host round trip on top of the "
                "guard's single sync (~1-2 ms each over a tunneled "
                "chip); read trainer.last_loss/last_grad_norm (already "
                "synced by the guard), sync once after the loop, or "
                "decimate the read (if step % N == 0)",
                node=f"{filename}:{getattr(node, 'lineno', 0)}",
                op=hit, pass_name="telemetry_lint",
                severity="warning"))


# -- MX603: stats through host callbacks in a jitted region ------------------

#: callback entry points that round-trip to host from inside a jit
_CALLBACK_LEAVES = {"pure_callback", "io_callback", "callback",
                    "debug_callback", "host_callback"}
#: jax.debug.<leaf> forms (print included: it IS a host callback)
_DEBUG_LEAVES = {"callback", "print"}
#: reduction callables whose presence in a callback's arguments marks
#: it as "stats leaving the graph through the side door"
_REDUCTION_LEAVES = {"min", "max", "mean", "sum", "std", "var", "norm",
                     "rms", "amin", "amax", "nanmin", "nanmax",
                     "nanmean", "histogram", "bincount", "quantile",
                     "percentile", "isfinite", "isnan", "any", "all"}
#: decorator names marking a function as jit-compiled
_JIT_NAMES = {"jit", "pjit"}


def _leaf_name(f: ast.AST) -> Optional[str]:
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_host_callback_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    leaf = _leaf_name(f)
    if leaf in _CALLBACK_LEAVES:
        return True
    # jax.debug.callback / jax.debug.print
    if leaf in _DEBUG_LEAVES and isinstance(f, ast.Attribute) \
            and isinstance(f.value, ast.Attribute) \
            and f.value.attr == "debug":
        return True
    return False


def _carries_reduction(call: ast.Call) -> bool:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, ast.Call) \
                    and _leaf_name(node.func) in _REDUCTION_LEAVES:
                return True
    return False


def _is_jit_decorator(dec: ast.AST) -> bool:
    # @jit / @jax.jit / @pjit / @partial(jax.jit, ...) / @jax.jit(...)
    if isinstance(dec, ast.Call):
        if _leaf_name(dec.func) in ("partial",):
            return any(_leaf_name(getattr(a, "func", a)) in _JIT_NAMES
                       or _leaf_name(a) in _JIT_NAMES for a in dec.args)
        dec = dec.func
    return _leaf_name(dec) in _JIT_NAMES


def _jitted_functions(tree: ast.Module) -> List[ast.AST]:
    """Functions provably jit-compiled in this file: jit-decorated, or
    passed by name as the first argument of a ``jit(...)`` call."""
    jitted_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _leaf_name(node.func) in _JIT_NAMES:
            if node.args and isinstance(node.args[0], ast.Name):
                jitted_names.add(node.args[0].id)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in jitted_names \
                or any(_is_jit_decorator(d) for d in node.decorator_list):
            out.append(node)
    return out


def _lint_callback_stats(tree: ast.Module, filename: str,
                         report: Report) -> None:
    """MX603 over every provably-jitted function in the module."""
    for func in _jitted_functions(tree):
        for node in ast.walk(func):
            if not _is_host_callback_call(node):
                continue
            if not _carries_reduction(node):
                continue   # custom-op style callbacks over raw tensors
                # are MX701's HLO-level business, not a stats smell
            report.add(Diagnostic(
                "MX603",
                f"tensor statistics leave the jitted function "
                f"{func.name}() through a host callback "
                f"({_leaf_name(node.func)}) — this breaks whole-step "
                "capture (one callback round-trip per executed step); "
                "compute the reduction in-graph and return it as an "
                "extra pinned output (telemetry.numerics.graph_stats/"
                "tap), decimating host-side",
                node=f"{filename}:{getattr(node, 'lineno', 0)}",
                op=func.name, pass_name="telemetry_lint",
                severity="warning"))


def lint_source(src: str, filename: str = "<string>") -> Report:
    """Lint one Python source blob for MX6xx findings."""
    report = Report()
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError:
        return report  # tracer_lint owns the MX200 parse diagnostic
    # MX602 runs unconditionally: emit() is its subject, so file-level
    # telemetry evidence cannot excuse it
    _lint_uncorrelated(tree, filename, report)
    # MX603 likewise: a host callback carrying reductions out of a jit
    # is the subject itself, never excused by other telemetry in the file
    _lint_callback_stats(tree, filename, report)
    # MX604 likewise: the stray sync IS the subject — a file full of
    # telemetry spine usage can still pay a hidden round trip per step
    _lint_stray_syncs(tree, filename, report)
    if _has_telemetry_evidence(tree):
        return report
    seen_clocks: Set[int] = set()  # one finding per scope; a clock call
    for where, scopes in (("training loop", _step_loops(tree)),  # inside
                          ("serving entry point",  # nested scopes reports
                           _entry_functions(tree))):  # at the outermost
        for scope in scopes:
            clocks = [n for n in ast.walk(scope)
                      if _is_clock_call(n) and id(n) not in seen_clocks]
            if not clocks:
                continue
            seen_clocks.update(id(n) for n in clocks)
            name = getattr(scope, "name", None)
            report.add(Diagnostic(
                "MX601",
                f"ad-hoc wall-clock timing inside a {where} "
                f"({len(clocks)} clock call(s)) — this measurement is "
                "invisible to the event bus, the Prometheus scrape, and "
                "telemetry.snapshot(); emit it through mx.telemetry "
                "(emit()/Histogram/step_scope) or an mx.profiler span "
                "instead",
                node=f"{filename}:{getattr(clocks[0], 'lineno', 0)}",
                op=name or where, pass_name="telemetry_lint",
                severity="warning"))
    return report


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_paths(paths) -> Report:
    """Lint files and directories (recursing into ``*.py``)."""
    return walk_lint(paths, lint_file)

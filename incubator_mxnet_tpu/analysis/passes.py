"""Analysis pass registry.

Reference counterpart: the nnvm pass registry (``nnvm::PassFunctionReg``,
``src/nnvm/pass.cc`` — passes are named, registered globally, declare what
they depend on, and are applied to a Graph by name). Graph passes here are
pure inspections: ``fn(PassContext) -> None`` appends
:class:`~.diagnostics.Diagnostic` rows and never mutates the Symbol (rewrites
live in ``mx.subgraph``; this layer only *judges* graphs).
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from .diagnostics import Diagnostic, Report

__all__ = ["PassContext", "GraphPass", "register_pass", "list_passes",
           "get_pass", "run_passes", "PASSES"]


@dataclass
class PassContext:
    """Everything a pass may consult. ``sym`` is the graph under analysis;
    the optional fields parameterize individual passes (the shape pass needs
    input ``shapes``, the sharding pass needs ``rules`` + ``mesh`` +
    parameter ``params``) — a pass that lacks its inputs records itself in
    ``report.skipped`` instead of failing."""

    sym: object = None
    shapes: Optional[Dict[str, tuple]] = None
    rules: object = None          # parallel.sharding.ShardingRules
    mesh: object = None           # jax.sharding.Mesh
    params: Optional[Dict[str, tuple]] = None  # param name -> shape
    report: Report = field(default_factory=Report)

    def diag(self, code: str, message: str, node: Optional[str] = None,
             op: Optional[str] = None, attrs: Optional[dict] = None,
             pass_name: str = "", severity: str = "error") -> None:
        self.report.add(Diagnostic(code, message, node=node, op=op,
                                   attrs=attrs, pass_name=pass_name,
                                   severity=severity))


@dataclass
class GraphPass:
    name: str
    fn: Callable[[PassContext], None]
    describe: str = ""

    def __call__(self, ctx: PassContext) -> None:
        self.fn(ctx)


#: name -> GraphPass, in registration order (= default execution order, the
#: nnvm convention: structural validity before semantic passes).
PASSES: "OrderedDict[str, GraphPass]" = OrderedDict()


def register_pass(name: Optional[str] = None, describe: str = ""):
    """Register an analysis pass; usable as ``@register_pass()`` or
    ``@register_pass("name", describe="...")`` — the ``NNVM_REGISTER_PASS``
    analogue."""

    def _do(fn: Callable[[PassContext], None]) -> Callable:
        pname = name or fn.__name__
        PASSES[pname] = GraphPass(pname, fn,
                                  describe or (fn.__doc__ or "").split("\n")[0])
        return fn

    return _do


def list_passes() -> List[str]:
    return list(PASSES)


def get_pass(name: str) -> GraphPass:
    if name not in PASSES:
        from ..base import MXNetError
        raise MXNetError(f"unknown analysis pass {name!r}; registered: "
                         f"{list_passes()}")
    return PASSES[name]


def run_passes(sym, names: Optional[Sequence[str]] = None,
               shapes: Optional[Dict[str, tuple]] = None,
               rules=None, mesh=None,
               params: Optional[Dict[str, tuple]] = None) -> Report:
    """Apply the named passes (default: all registered, in order) to one
    Symbol and return the merged Report."""
    ctx = PassContext(sym=sym, shapes=shapes, rules=rules, mesh=mesh,
                      params=params)
    for name in (names if names is not None else list_passes()):
        get_pass(name)(ctx)
    return ctx.report

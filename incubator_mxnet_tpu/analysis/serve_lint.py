"""Serving-hygiene linter (the MX5xx family).

Companion to :mod:`.tracer_lint` (protects the compiled graph from Python)
and :mod:`.fault_lint` (protects the run from the machine): this pass
protects the *request path* from the jit cache. On a jit runtime every
distinct input shape is a fresh XLA compile — seconds of tail latency
injected into whichever request drew the new shape — so an inference entry
point must (a) compile once, outside the request loop, and (b) quantize
request shapes onto warmed buckets (``mx.serve.BucketTable`` /
``CompiledModel.warmup``). Two pure-AST checks, warning severity
(perf hazards, same contract as MX201/MX401; ``mxlint --strict`` gates):

- **MX501** — a compile-constructing call (``jax.jit``, ``.hybridize()``,
  ``serve.CompiledModel``) inside a ``for``/``while`` body:
  the classic re-trace-per-request bug; hoist it out of the loop and warm
  up once.
- **MX502** — a serving entry point (a function named ``predict`` /
  ``serve`` / ``infer`` / ``handle`` / ``handle_request``) feeds one of
  its own raw parameters straight to a jitted/hybridized callable, and
  the file shows no bucketing/warmup evidence at all: every novel request
  shape will compile. Routing through ``mx.serve`` (``CompiledModel``,
  ``DynamicBatcher``…) or any ``BucketTable``/``warmup`` use counts as
  evidence, so the serve runtime and code built on it lint clean.

Heuristics are tuned for zero noise on non-serving files: MX502 requires
all three legs (entry-point name, jit-bound callee, raw parameter
argument) before it fires.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .diagnostics import Diagnostic, Report, walk_lint

__all__ = ["lint_source", "lint_file", "lint_paths"]

#: function/method names treated as request-serving entry points
_ENTRY_NAMES = {"predict", "serve", "infer", "inference", "handle",
                "handle_request"}

#: any of these identifiers anywhere in the file = the code already
#: thinks in buckets / uses the serve runtime — MX502 stays quiet
_BUCKET_EVIDENCE = {"BucketTable", "bucket", "bucket_for", "assignment",
                    "round_up_pow2", "warmup", "CompiledModel",
                    "DynamicBatcher", "ModelRegistry", "export_for_serving"}

#: attribute/function leaf names whose call constructs a compile
#: (``.lower()`` is deliberately absent — too common on strings)
_COMPILE_NAMES = {"jit", "hybridize", "CompiledModel"}


def _call_is_compile(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _COMPILE_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr in _COMPILE_NAMES
    return False


def _jit_bound_names(tree: ast.Module) -> Set[str]:
    """Names (incl. attribute leaf names) bound to a jit/hybridized
    callable anywhere in the file: ``model = jax.jit(f)``,
    ``self.fn = jit(f)``, plus receivers of ``.hybridize()``."""
    bound: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = node.value.func
            is_jit = (isinstance(f, ast.Name) and f.id == "jit") or \
                (isinstance(f, ast.Attribute) and f.attr == "jit")
            if is_jit:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        bound.add(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        bound.add(tgt.attr)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "hybridize":
            recv = node.func.value
            if isinstance(recv, ast.Name):
                bound.add(recv.id)
            elif isinstance(recv, ast.Attribute):
                bound.add(recv.attr)
    return bound


def _has_bucket_evidence(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in _BUCKET_EVIDENCE:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _BUCKET_EVIDENCE:
            return True
    return False


def _lint_mx501(tree: ast.Module, filename: str, report: Report) -> None:
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for node in ast.walk(loop):
            if node is loop:
                continue
            # nested loops report at their own visit
            if isinstance(node, ast.Call) and _call_is_compile(node):
                what = (node.func.attr if isinstance(node.func, ast.Attribute)
                        else node.func.id)
                report.add(Diagnostic(
                    "MX501",
                    f"{what}() inside a loop compiles/re-traces per "
                    "iteration — seconds of latency per request; build the "
                    "compiled callable once outside the loop and warmup() "
                    "its shape buckets (mx.serve.CompiledModel)",
                    node=f"{filename}:{getattr(node, 'lineno', 0)}",
                    op=what, pass_name="serve_lint", severity="warning"))


def _lint_mx502(tree: ast.Module, filename: str, report: Report) -> None:
    if _has_bucket_evidence(tree):
        return
    jit_names = _jit_bound_names(tree)
    if not jit_names:
        return
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or fn.name not in _ENTRY_NAMES:
            continue
        args = fn.args
        params = {a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs} - {"self", "cls"}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            callee = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if callee not in jit_names:
                continue
            raw = [a.id for a in node.args
                   if isinstance(a, ast.Name) and a.id in params]
            if raw:
                report.add(Diagnostic(
                    "MX502",
                    f"serving entry point {fn.name}() feeds raw request "
                    f"argument(s) {raw} to the jitted callable "
                    f"{callee!r} — every novel request shape is a fresh "
                    "XLA compile; pad onto a warmed "
                    "mx.serve.BucketTable first",
                    node=f"{filename}:{getattr(node, 'lineno', 0)}",
                    op=f"{fn.name}", pass_name="serve_lint",
                    severity="warning"))


def lint_source(src: str, filename: str = "<string>") -> Report:
    """Lint one Python source blob for MX5xx findings."""
    report = Report()
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError:
        return report  # tracer_lint owns the MX200 parse diagnostic
    _lint_mx501(tree, filename, report)
    _lint_mx502(tree, filename, report)
    # nested-loop duplicates (outer AND inner loop visit the same call)
    seen = set()
    deduped = Report()
    deduped.skipped.extend(report.skipped)
    for d in report.diagnostics:
        key = (d.code, d.node, d.op)
        if key not in seen:
            seen.add(key)
            deduped.add(d)
    return deduped


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_paths(paths) -> Report:
    """Lint files and directories (recursing into ``*.py``)."""
    return walk_lint(paths, lint_file)

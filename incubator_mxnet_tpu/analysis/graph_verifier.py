"""Whole-graph structural verifier (pass 1).

Reference counterpart: the validation nnvm does piecemeal at pass time —
``InferShape``/``InferType`` arity checks, op-attr parsing through
``dmlc::Parameter``, and the JSON loader's index checks
(``src/nnvm/graph.cc``). Here it is ONE inspection pass over the Symbol DAG:

- **MX001** cycle detection (a malformed graph must fail here, not hang a
  later walk),
- **MX002** duplicate node names (serialization and Monitor capture key by
  name),
- **MX003** ops missing from the registry,
- **MX004** input arity vs the registered op's tensor slots (introspected
  from the op function's signature minus its Schema fields),
- **MX005** per-node re-validation of attrs against the op's declared
  ``Schema`` (the dmlc::Parameter contract, checked *after* composition so
  hand-built or deserialized graphs are covered too),
- **MX006** JSON wire-format round-trip stability (``tojson`` →
  ``load_json`` → ``tojson`` must converge, including nested ``sub``-attr
  subgraphs from the control-flow ops and ``subgraph.py`` partitioning).

Subgraphs riding in node attrs (control flow bodies, ``_subgraph_exec``
regions) are verified recursively with ``parent/child`` provenance.
"""
from __future__ import annotations

import inspect
import json
from typing import Dict, List, Optional, Tuple

from .diagnostics import Report
from .passes import PassContext, register_pass

__all__ = ["verify_graph", "tensor_arity"]

#: structural pseudo-ops that never appear in the op registry
_STRUCTURAL_OPS = {"_group"}


def _children(node) -> List:
    out = list(node._inputs)
    if node._base is not None:
        out.append(node._base)
    return out


def _find_cycle(root) -> Optional[str]:
    """Iterative three-color DFS; returns the name of a node on a cycle."""
    GREY, BLACK = 1, 2
    color: Dict[int, int] = {}
    stack: List[Tuple[object, iter]] = [(root, iter(_children(root)))]
    color[id(root)] = GREY
    while stack:
        node, it = stack[-1]
        child = next(it, None)
        if child is None:
            color[id(node)] = BLACK
            stack.pop()
            continue
        c = color.get(id(child))
        if c == GREY:
            return child._name
        if c is None:
            color[id(child)] = GREY
            stack.append((child, iter(_children(child))))
    return None


def _collect(root) -> List:
    """All reachable nodes (inputs + base edges), deterministic order."""
    seen: Dict[int, object] = {}
    order: List = []
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen[id(node)] = node
        order.append(node)
        stack.extend(reversed(_children(node)))
    return order


def tensor_arity(opdef) -> Optional[Tuple[int, Optional[int]]]:
    """(min, max) tensor-input slots of a registered op: positional
    parameters of the op function that are not Schema fields. ``max`` is
    None for variadic ops (``*arrays``); returns None when the signature
    cannot be introspected."""
    try:
        sig = inspect.signature(opdef.fn)
    except (TypeError, ValueError):
        return None
    fields = opdef.schema.fields if opdef.schema is not None else {}
    lo, hi = 0, 0
    for p in sig.parameters.values():
        if p.kind is p.VAR_POSITIONAL:
            return lo, None
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            if p.name in fields:
                continue
            hi += 1
            if p.default is p.empty:
                lo += 1
    return lo, hi


def _public_attrs(node) -> dict:
    return {k: v for k, v in node._attrs.items() if not k.startswith("_")}


def _sub_symbols(attrs):
    """(key, root Symbol) pairs for every subgraph riding in an attr dict:
    a bare Symbol value, a list of Symbols, or the control-flow/partitioner
    ``sub`` wire shape ``{"roots": [...], "arg_names": [...]}``."""
    from .. import symbol as S
    for k, v in attrs.items():
        if isinstance(v, S.Symbol):
            yield k, v
        elif isinstance(v, dict) and isinstance(v.get("roots"), (list, tuple)):
            for i, r in enumerate(v["roots"]):
                if isinstance(r, S.Symbol):
                    yield f"{k}.roots[{i}]", r
        elif isinstance(v, (list, tuple)):
            for i, r in enumerate(v):
                if isinstance(r, S.Symbol):
                    yield f"{k}[{i}]", r


def _check_nodes(ctx: PassContext, root, prefix: str = "") -> None:
    """Cycle, name, registry, arity and schema checks for one graph level;
    recurses into attr subgraphs with ``prefix`` provenance."""
    from .. import symbol as S
    from ..ops import OPS

    cyc = _find_cycle(root)
    if cyc is not None:
        ctx.diag("MX001", "graph contains a cycle (reached its own "
                 "ancestor); downstream checks skipped for this graph",
                 node=prefix + cyc, pass_name="graph_verify")
        return

    nodes = _collect(root)

    # Multi-output slices are counted once per (base, output index):
    # Symbol.__getitem__ mints a fresh node per access, so the same logical
    # slice can be reachable several times under one (deterministic) name.
    by_name: Dict[str, int] = {}
    slices_seen = set()
    for n in nodes:
        if n._base is not None:
            key = (id(n._base), n._output_index)
            if key in slices_seen:
                continue
            slices_seen.add(key)
        by_name[n._name] = by_name.get(n._name, 0) + 1
    for name, count in sorted(by_name.items()):
        if count > 1:
            ctx.diag("MX002", f"{count} distinct nodes share the name "
                     f"{name!r}; serialization and Monitor capture key by "
                     "name", node=prefix + name, pass_name="graph_verify")

    for n in nodes:
        if n._base is not None:  # multi-output slice: only the index is its
            if n._output_index >= n._base._num_outputs:  # own to check
                ctx.diag("MX008", f"output index {n._output_index} out of "
                         f"range: base '{n._base._name}' declares "
                         f"{n._base._num_outputs} output(s)",
                         node=prefix + n._name, op=n._base._op,
                         pass_name="graph_verify")
            continue
        if n._op is None:
            if n._inputs:
                ctx.diag("MX004", "variable node has inputs "
                         f"({len(n._inputs)}); variables must be leaves",
                         node=prefix + n._name, op="null",
                         pass_name="graph_verify")
            continue
        if n._op in _STRUCTURAL_OPS:
            continue
        if n._op in S._SCALAR_OPS:
            if len(n._inputs) != 1:
                ctx.diag("MX004", f"scalar op takes exactly 1 input, got "
                         f"{len(n._inputs)}", node=prefix + n._name,
                         op=n._op, pass_name="graph_verify")
            continue
        opdef = OPS.get(n._op)
        if opdef is None:
            ctx.diag("MX003", f"op {n._op!r} is not in the op registry "
                     "(unknown or unregistered at load time)",
                     node=prefix + n._name, op=n._op,
                     pass_name="graph_verify")
            continue
        arity = tensor_arity(opdef)
        if arity is not None:
            lo, hi = arity
            got = len(n._inputs)
            if got < lo or (hi is not None and got > hi):
                want = f"{lo}" if hi == lo else (
                    f"{lo}+" if hi is None else f"{lo}..{hi}")
                ctx.diag("MX004", f"op expects {want} tensor input(s), "
                         f"got {got}", node=prefix + n._name, op=n._op,
                         attrs=_public_attrs(n), pass_name="graph_verify")
        if opdef.schema is not None:
            attrs = _public_attrs(n)
            try:
                opdef.schema.validate(opdef.name, attrs)
            except (TypeError, ValueError) as e:
                ctx.diag("MX005", str(e), node=prefix + n._name, op=n._op,
                         attrs=attrs, pass_name="graph_verify")
        for key, sub in _sub_symbols(n._attrs):
            _check_nodes(ctx, sub, prefix=f"{prefix}{n._name}.{key}/")


def _check_roundtrip(ctx: PassContext, root) -> None:
    from .. import symbol as S
    try:
        j1 = root.tojson()
        j2 = S.load_json(j1).tojson()
    except Exception as e:  # unserializable attr, loader failure, ...
        ctx.diag("MX006", f"JSON round-trip raised {type(e).__name__}: {e}",
                 node=root._name, pass_name="graph_verify")
        return
    if json.loads(j1) != json.loads(j2):
        ctx.diag("MX006", "serialize -> load -> serialize does not "
                 "converge: an attr value does not survive the wire format "
                 "(repr/literal_eval round-trip)", node=root._name,
                 pass_name="graph_verify")


@register_pass("graph_verify",
               describe="structure, registry, arity, Schema and JSON "
                        "round-trip checks (MX001-MX006)")
def verify_graph(ctx: PassContext) -> None:
    """Structural verifier over ``ctx.sym`` — see module docstring."""
    before = len(ctx.report.diagnostics)
    _check_nodes(ctx, ctx.sym)
    cyclic = any(d.code == "MX001"
                 for d in ctx.report.diagnostics[before:])
    if not cyclic:  # a cyclic graph cannot be serialized meaningfully
        _check_roundtrip(ctx, ctx.sym)

"""Fault-tolerance hygiene linter (the MX4xx family).

Companion to :mod:`.tracer_lint`: where that pass protects the *compiled
graph* from Python, this one protects the *run* from the machine. The one
production incident every long training job eventually hits is dying with
no checkpoint — so MX401 flags training scripts that construct a
``ShardedTrainer``/``gluon.Trainer`` and drive it through a step loop
without ever calling a checkpointing API (``save_checkpoint``,
``save_states``, ``save_parameters``, or ``fault.checkpoint.*``).

The check is deliberately coarse (pure-AST, per-file, no imports of the
linted code — same contract as the tracer lint) and reports at
``warning`` severity: a missing checkpoint is a durability hazard, not a
correctness error, and short experiment scripts legitimately skip it
(``mxlint --strict`` promotes warnings to a failing exit).

Heuristics, tuned for zero noise on non-training files:

- a *trainer construction* is any call whose callee name (or trailing
  attribute) is ``ShardedTrainer`` or ``Trainer``;
- a *training loop* is a ``for``/``while`` whose body calls ``.step(...)``
  or a trainer method — files that build a trainer but never loop (unit
  helpers, factories) are not flagged;
- *checkpoint evidence* is any call (anywhere in the file, incl. helper
  functions) to one of the checkpointing APIs above.
"""
from __future__ import annotations

import ast
from typing import List

from .diagnostics import Diagnostic, Report, walk_lint

__all__ = ["lint_source", "lint_file", "lint_paths"]

_TRAINER_NAMES = {"ShardedTrainer", "Trainer"}

#: any of these calls, anywhere in the file, counts as checkpointing
_CHECKPOINT_CALLS = {
    "save_checkpoint", "restore_checkpoint", "load_checkpoint",
    "load_latest", "save_states", "load_states",
    "save_parameters", "save_params",
    "save_optimizer_states", "export",
}


def _callee_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _has_step_loop(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for inner in ast.walk(node):
            if isinstance(inner, ast.Call) \
                    and _callee_name(inner) == "step":
                return True
    return False


def lint_source(src: str, filename: str = "<string>") -> Report:
    """Lint one Python source blob for MX4xx findings."""
    report = Report()
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError:
        return report  # tracer_lint owns the MX200 parse diagnostic
    trainer_ctors: List[ast.Call] = [
        n for n in ast.walk(tree)
        if isinstance(n, ast.Call) and _callee_name(n) in _TRAINER_NAMES]
    if not trainer_ctors or not _has_step_loop(tree):
        return report
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _callee_name(node) in _CHECKPOINT_CALLS:
            return report
    ctor = trainer_ctors[0]
    report.add(Diagnostic(
        "MX401",
        "this script builds a trainer and runs a step loop but never "
        "checkpoints — a preemption/NaN/crash loses the whole run; call "
        "trainer.save_checkpoint(dir) periodically (mx.fault restores "
        "from the newest verified step)",
        node=f"{filename}:{getattr(ctor, 'lineno', 0)}",
        op=_callee_name(ctor), pass_name="fault_lint",
        severity="warning"))
    return report


def lint_file(path: str) -> Report:
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_paths(paths) -> Report:
    """Lint files and directories (recursing into ``*.py``)."""
    return walk_lint(paths, lint_file)

"""On-disk autotune cache — persisted per-config tuning winners.

Reference counterpart: TVM's tuning-log reuse (arXiv 1802.04799) — search
once, persist the best schedule per (workload, target), and every later
build consults the log instead of re-searching or hand-picking env knobs.
Here the "schedule" is a small dict of runtime knobs (flash-attention
block sizes, embedding-gradient path, remat policy, batch/bucket
geometry) found by the device-blind search driver
``benchmark/autotune.py`` and scored by ``analysis.hlo.cost`` plus the
compile ledger.

Winners persist per ``(model, mesh_shape, chip)`` key with the same
integrity discipline as :class:`~incubator_mxnet_tpu.serve.artifact_cache
.ArtifactCache`: canonical-JSON payload + CRC32, written to a temp file
finalized by one atomic ``os.replace``; a corrupt entry is evicted and
reported as a miss, never applied.

Both build sites consult the cache when ``MXTPU_AUTOTUNE_DIR`` is set:

- :class:`~incubator_mxnet_tpu.parallel.trainer.ShardedTrainer` before
  tracing its compiled step (site ``trainer.step`` — the same name its
  compiles carry on the telemetry compile ledger);
- :class:`~incubator_mxnet_tpu.serve.compiled.CompiledModel` around each
  bucket's AOT compile (site ``serve.compiled``).

Every consult publishes an ``autotune.consult`` event carrying the
ledger site name, so a tuned build is attributable end to end: the
consult event and the compile record share the site string. Explicitly
user-set environment variables always win over a cached winner —
:func:`applied` only fills knobs the environment leaves unset.
"""
from __future__ import annotations

import json
import os
import zlib
from contextlib import contextmanager
from typing import Any, Dict, Optional

from .base import MXNetError
from .lockcheck import make_lock

__all__ = ["AutotuneCache", "AutotuneCorruptError", "default_cache",
           "consult", "applied", "mesh_desc", "chip_kind", "TUNABLE_ENV"]

#: env knobs a cached winner may carry — the applied() allowlist, so a
#: corrupted/hostile cache entry can never set arbitrary variables
TUNABLE_ENV = (
    "MXTPU_FLASH_BK", "MXTPU_FLASH_BQ", "MXTPU_EMBED_ONEHOT_GRAD",
)

_FORMAT = 1


class AutotuneCorruptError(MXNetError):
    """A cache entry exists but fails CRC/format verification."""


def mesh_desc(mesh=None) -> str:
    """Canonical mesh-shape key component: ``"dp2tp4"`` for a configured
    mesh, ``"single"`` for no mesh / one device. Lookups additionally
    fall back to ``"any"`` — the key the device-blind search driver
    banks under (its cost-model score is mesh-portable)."""
    if mesh is None:
        return "single"
    shape = dict(getattr(mesh, "shape", {}) or {})
    if not shape or all(v == 1 for v in shape.values()):
        return "single"
    return "".join(f"{k}{v}" for k, v in sorted(shape.items()))


def chip_kind() -> str:
    """Normalized accelerator kind of the default backend's first device
    (``"cpu"``, ``"tpu-v5e"``...) — the hardware half of the cache key."""
    import jax
    kind = jax.devices()[0].device_kind
    return str(kind).strip().lower().replace(" ", "-")


class AutotuneCache:
    """Directory of verified tuning winners, one JSON file per key.

    Layout::

        <root>/<model>/<mesh_shape>-<chip>.json

    Each file holds ``{"format", "model", "mesh", "chip", "jax",
    "config": {"env": {...}, "geometry": {...}}, "score", "meta",
    "crc"}`` where ``crc`` is the CRC32 of the canonical (sorted-key)
    JSON of everything else — the same torn-write/bit-rot discipline as
    the serve artifact cache, sized for a dict instead of StableHLO.
    """

    def __init__(self, root: str):
        self.root = root
        self._lock = make_lock("AutotuneCache._lock")
        self.stats = {"hits": 0, "misses": 0, "corrupt": 0, "puts": 0}

    # -- key / paths -----------------------------------------------------
    @staticmethod
    def _safe(part: str) -> str:
        keep = [c if (c.isalnum() or c in "._-") else "_" for c in str(part)]
        return "".join(keep) or "_"

    def entry_path(self, model: str, mesh_shape: str, chip: str) -> str:
        return os.path.join(self.root, self._safe(model),
                            f"{self._safe(mesh_shape)}-{self._safe(chip)}"
                            ".json")

    def _note(self, outcome: str, model: str, mesh_shape: str, chip: str,
              **fields) -> None:
        key = {"hit": "hits", "miss": "misses", "corrupt": "corrupt",
               "put": "puts"}[outcome]
        with self._lock:
            self.stats[key] += 1
        from .telemetry import events as _tele
        from .telemetry import metrics as _tmetrics
        _tele.emit("autotune.cache",
                   severity="warning" if outcome == "corrupt" else "info",
                   model=model, mesh=mesh_shape, chip=chip,
                   outcome=outcome, **fields)
        _tmetrics.counter("mxtpu_autotune_cache_total",
                          "Autotune-cache lookups/writes by outcome",
                          outcome=outcome).inc()

    # -- write path ------------------------------------------------------
    @staticmethod
    def _payload_crc(doc: Dict[str, Any]) -> int:
        body = {k: v for k, v in doc.items() if k != "crc"}
        return zlib.crc32(
            json.dumps(body, sort_keys=True).encode("utf-8")) & 0xFFFFFFFF

    def put(self, model: str, mesh_shape: str, chip: str,
            config: Dict[str, Any], score: float,
            meta: Optional[Dict[str, Any]] = None) -> str:
        """Persist one winner atomically; returns the entry path.
        ``config`` splits into ``env`` (the applied knobs, filtered to
        :data:`TUNABLE_ENV` on read) and free-form ``geometry``."""
        import jax
        path = self.entry_path(model, mesh_shape, chip)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc = {
            "format": _FORMAT, "model": str(model),
            "mesh": str(mesh_shape), "chip": str(chip),
            "jax": jax.__version__,
            "config": config, "score": float(score),
            "meta": dict(meta or {}),
        }
        doc["crc"] = self._payload_crc(doc)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._note("put", model, mesh_shape, chip, score=float(score))
        return path

    # -- read path -------------------------------------------------------
    def get(self, model: str, mesh_shape: str = "single",
            chip: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Verified lookup → the entry dict on a hit, ``None`` on a
        miss. Falls back from the exact mesh key to the driver's
        ``"any"`` key. A corrupt entry (CRC/format mismatch) is evicted
        and reported as a miss so the caller builds untuned."""
        chip = chip if chip is not None else chip_kind()
        for mesh_key in dict.fromkeys((mesh_shape, "any")):
            path = self.entry_path(model, mesh_key, chip)
            if not os.path.isfile(path):
                continue
            try:
                entry = self._verify(path)
            except (AutotuneCorruptError, OSError) as e:
                self._note("corrupt", model, mesh_key, chip,
                           error=str(e)[:200])
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            self._note("hit", model, mesh_key, chip,
                       score=entry.get("score"))
            return entry
        self._note("miss", model, mesh_shape, chip)
        return None

    def _verify(self, path: str) -> Dict[str, Any]:
        try:
            with open(path) as f:
                entry = json.load(f)
        except (OSError, ValueError) as e:
            raise AutotuneCorruptError(
                f"{path}: unreadable entry: {e}") from e
        if not isinstance(entry, dict) or entry.get("format") != _FORMAT:
            raise AutotuneCorruptError(
                f"{path}: unknown format {entry.get('format')!r}"
                if isinstance(entry, dict) else f"{path}: not an object")
        if self._payload_crc(entry) != entry.get("crc"):
            raise AutotuneCorruptError(
                f"{path}: checksum mismatch (entry {entry.get('crc')}, "
                f"payload {self._payload_crc(entry)})")
        return entry

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.stats)


# -- build-time consult ------------------------------------------------------

def enabled() -> bool:
    """True when builds should consult the cache: ``MXTPU_AUTOTUNE_DIR``
    names a directory and ``MXTPU_AUTOTUNE`` is not ``0``. Both reads
    are plain env lookups — the off path costs nothing on the hot
    build."""
    return bool(os.environ.get("MXTPU_AUTOTUNE_DIR")) \
        and os.environ.get("MXTPU_AUTOTUNE", "1") == "1"


def default_cache() -> Optional[AutotuneCache]:
    """The process cache at ``MXTPU_AUTOTUNE_DIR`` (None when consulting
    is disabled). Constructed per call — the object is a thin path
    wrapper; entries live on disk."""
    if not enabled():
        return None
    return AutotuneCache(os.environ["MXTPU_AUTOTUNE_DIR"])


def consult(site: str, model: str, mesh=None) -> Optional[Dict[str, Any]]:
    """Build-time lookup for ``site`` (the compile-ledger site name the
    caller's compiles are recorded under — ``trainer.step`` /
    ``serve.compiled``). Returns the winning entry or ``None``; emits
    one ``autotune.consult`` event either way so a tuned build is
    attributable to its cache entry on the same timeline as its compile
    record."""
    cache = default_cache()
    if cache is None:
        return None
    entry = cache.get(model, mesh_desc(mesh))
    from .telemetry import events as _tele
    _tele.emit("autotune.consult", site=site, model=model,
               mesh=mesh_desc(mesh), chip=chip_kind(),
               outcome="hit" if entry is not None else "miss",
               config=(entry or {}).get("config"),
               score=(entry or {}).get("score"))
    return entry


@contextmanager
def applied(entry: Optional[Dict[str, Any]], force: bool = False):
    """Overlay a winner's env knobs for the duration of a trace/compile.

    Only keys in :data:`TUNABLE_ENV` apply, and (unless ``force``) only
    keys the user did NOT set explicitly — an operator's hand-pinned
    ``MXTPU_FLASH_BK`` beats the cache. Values restore on exit, so the
    overlay is scoped to the build, not leaked into the process."""
    env = {}
    if entry:
        cfg = entry.get("config", entry)
        env = {k: str(v) for k, v in (cfg.get("env") or {}).items()
               if k in TUNABLE_ENV and v is not None
               and (force or k not in os.environ)}
    saved = {k: os.environ.get(k) for k in env}
    try:
        os.environ.update(env)
        yield env
    finally:
        for k, prev in saved.items():
            if prev is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = prev

"""Test harness library (reference: ``python/mxnet/test_utils.py`` —
``assert_almost_equal``, ``check_numeric_gradient``, ``check_consistency``,
``rand_ndarray``, ``default_context``; SURVEY §4).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as onp

from . import autograd
from .base import _as_list
from .context import Context, cpu, current_context
from .ndarray import NDArray, array
from . import ndarray as nd

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
    "rand_shape_nd", "check_numeric_gradient", "check_consistency",
    "default_dtype", "effective_dtype_tol",
]

_default_ctx: Optional[Context] = None


def default_context() -> Context:
    if _default_ctx is not None:
        return _default_ctx
    return current_context()


def set_default_context(ctx: Optional[Context]) -> None:
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return onp.float32


def effective_dtype_tol(dtype) -> float:
    dt = onp.dtype(dtype)
    return {"float16": 1e-2, "bfloat16": 2e-2, "float32": 1e-4, "float64": 1e-6}.get(dt.name, 1e-4)


def _to_numpy(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def same(a, b) -> bool:
    return onp.array_equal(_to_numpy(a), _to_numpy(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20) -> bool:
    return onp.allclose(_to_numpy(a), _to_numpy(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")) -> None:
    a_, b_ = _to_numpy(a), _to_numpy(b)
    if rtol is None:
        rtol = max(effective_dtype_tol(a_.dtype), effective_dtype_tol(b_.dtype)) \
            if a_.dtype.kind == "f" else 1e-5
    if atol is None:
        atol = rtol
    onp.testing.assert_allclose(a_.astype(onp.float64), b_.astype(onp.float64),
                                rtol=rtol, atol=atol,
                                err_msg=f"{names[0]} vs {names[1]} mismatch")


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None,
                 scale=1.0) -> NDArray:
    ctx = ctx or default_context()
    dtype = dtype or onp.float32
    data = onp.random.uniform(-scale, scale, size=shape).astype(dtype)
    arr = array(data, ctx=ctx)
    if stype == "default":
        return arr
    from .ndarray import sparse
    return sparse.cast_storage(arr, stype)


def numeric_grad(executor_fn: Callable, inputs: List[onp.ndarray], eps=1e-4) -> List[onp.ndarray]:
    """Central finite differences of a scalar-output function."""
    grads = []
    for i, x in enumerate(inputs):
        g = onp.zeros_like(x, dtype=onp.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(executor_fn(*inputs))
            flat[j] = orig - eps
            fm = float(executor_fn(*inputs))
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(fn: Callable, inputs: Sequence, rtol=1e-2, atol=1e-3,
                           eps=1e-3, ctx=None) -> None:
    """Compare autograd gradients of ``sum(fn(*inputs))`` against central
    finite differences (reference: test_utils.check_numeric_gradient)."""
    ctx = ctx or default_context()
    arrs = [x if isinstance(x, NDArray) else array(onp.asarray(x, onp.float32), ctx=ctx)
            for x in inputs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs)
        loss = out.sum() if out.ndim > 0 else out
    loss.backward()
    analytic = [a.grad.asnumpy().astype(onp.float64) for a in arrs]

    def host_fn(*np_inputs):
        outs = fn(*[array(x.astype(onp.float32), ctx=ctx) for x in np_inputs])
        return outs.sum().asnumpy()

    numeric = numeric_grad(host_fn, [a.asnumpy().astype(onp.float64) for a in arrs], eps=eps)
    for an, nu in zip(analytic, numeric):
        onp.testing.assert_allclose(an, nu, rtol=rtol, atol=atol,
                                    err_msg="autograd vs finite-difference mismatch")


def check_consistency(fn: Callable, inputs_np: Sequence[onp.ndarray],
                      ctx_list: Optional[Sequence[Context]] = None,
                      dtypes=("float32",), rtol=None, atol=None) -> None:
    """Run the same computation across contexts/dtypes and compare
    (reference: check_consistency cross-device numerics)."""
    ctx_list = list(ctx_list) if ctx_list else [cpu(0), default_context()]
    ref = None
    for ctx in ctx_list:
        for dt in dtypes:
            ins = [array(x.astype(dt), ctx=ctx) for x in inputs_np]
            out = fn(*ins).asnumpy().astype(onp.float64)
            if ref is None:
                ref = out
            else:
                tol = rtol if rtol is not None else effective_dtype_tol(dt)
                onp.testing.assert_allclose(out, ref, rtol=tol, atol=atol or tol,
                                            err_msg=f"inconsistent result on {ctx} {dt}")

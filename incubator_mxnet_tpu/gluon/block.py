"""Block / HybridBlock — the Gluon module system.

Reference parity: ``python/mxnet/gluon/block.py`` (``Block``,
``HybridBlock._build_cache``, ``HybridBlock.export``) — SURVEY §2.8, §3.3.

TPU-native design: ``hybridize()`` ≙ ``jax.jit``. The reference's first
hybridized call traces ``hybrid_forward`` with Symbol proxies into an nnvm
graph executed by ``CachedOp`` (src/imperative/cached_op.cc). Here the first
call runs eagerly (finishing deferred parameter init); subsequent calls run a
jit-compiled pure function whose inputs are (rng key, every descendant
parameter, the data arguments) and whose outputs are (forward outputs, traced
aux-state updates). Gradients flow through the cached op as a single autograd
tape node differentiated with ``jax.vjp`` — exactly the reference's
"CachedOp::Backward over the captured graph" collapsed onto XLA.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError, _as_list
from ..context import Context, cpu, current_context
from .. import autograd
from .. import random as random_mod
from ..ndarray import NDArray
from ..analysis.recompile import note_compile
from . import _trace
from .parameter import DeferredInitializationError, Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope(threading.local):
    """Name manager: numbers block instances per type (dense0_, dense1_ …).

    Reference: ``_BlockScope`` in python/mxnet/gluon/block.py.
    """

    _current = threading.local()

    def __init__(self, block=None):
        self._block = block
        self._counter: Dict[str, int] = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _GLOBAL_SCOPE._next_prefix(hint)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def _next_prefix(self, hint):
        count = self._counter.get(hint, 0)
        self._counter[hint] = count + 1
        return f"{hint}{count}_"

    def __enter__(self):
        if self._block is not None and self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block is not None and self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_GLOBAL_SCOPE = _BlockScope()

# True while a HybridBlock cache trace is in flight: nested hybridized
# children must run their eager path inside the parent's single trace.
_TRACING = threading.local()


def _is_tracing() -> bool:
    return getattr(_TRACING, "flag", False)


def _flatten_args(args):
    """Flatten (nested lists/tuples of) NDArrays; return (flat, fmt)."""
    flat: List[NDArray] = []

    def rec(a):
        if isinstance(a, NDArray):
            flat.append(a)
            return 0
        if isinstance(a, (list, tuple)):
            return [rec(x) for x in a]
        flat.append(a)  # non-array static leaf
        return -1

    fmt = [rec(a) for a in args]
    return flat, fmt


class _ArrSlot:
    """Placeholder for an NDArray position in a cached-arg skeleton (so the
    jit closure doesn't pin the cache-building batch's device buffers)."""

    __slots__ = ()


_ARR_SLOT = _ArrSlot()


def _strip_arrays(args):
    def rec(a):
        if isinstance(a, NDArray):
            return _ARR_SLOT
        if isinstance(a, (list, tuple)):
            return [rec(x) for x in a]
        return a

    return tuple(rec(a) for a in args)


def _static_key(flat_args):
    """Hashable digest of the non-array leaves (they are baked into the
    traced graph, so they must key the cache)."""
    out = []
    for a in flat_args:
        if isinstance(a, NDArray):
            continue
        try:
            hash(a)
            out.append(a)
        except TypeError:
            out.append(repr(a))
    return tuple(out)


def _regroup(flat, fmt):
    it = iter(flat)

    def rec(f):
        if f == 0 or f == -1:
            return next(it)
        return [rec(x) for x in f]

    return [rec(f) for f in fmt]


class Block:
    """Base class for all neural network layers and models."""

    def __init__(self, prefix: Optional[str] = None, params: Optional[ParameterDict] = None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()

    def _alias(self) -> str:
        return self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def name(self) -> str:
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        """All Parameters of this block and its descendants, optionally
        filtered by regex (reference: Block.collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret._params.update(
                {k: v for k, v in self.params.items() if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def _collect_params_with_prefix(self, prefix: str = "") -> Dict[str, Parameter]:
        if prefix:
            prefix += "."
        ret = {prefix + k.lstrip("_"): v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(value, type(existing)):
                raise TypeError(
                    f"Changing attribute type for {self.name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            if name in self.__dict__.get("_reg_params", {}):
                pass
            self.__dict__.setdefault("_reg_params", {})[name] = value
        super().__setattr__(name, value)

    def register_child(self, block: "Block", name: Optional[str] = None) -> None:
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook: Callable):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_hook(self, hook: Callable):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle.id] = hook
        return handle

    def apply(self, fn: Callable) -> "Block":
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose: bool = False,
                   force_reinit: bool = False) -> None:
        from .. import initializer as init_mod
        self.collect_params().initialize(
            init or init_mod.Xavier(), ctx, verbose, force_reinit)

    def cast(self, dtype) -> None:
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def zero_grad(self) -> None:
        self.collect_params().zero_grad()

    def hybridize(self, active: bool = True, **kwargs) -> None:
        """No-op at Block level; HybridBlock overrides (reference parity:
        plain Blocks just cascade to children)."""
        for child in self._children.values():
            # cascading a mode flag, not re-tracing per request
            child.hybridize(active, **kwargs)  # mxlint: disable=MX501

    # ------------------------------------------------------------------
    # checkpointing (SURVEY §5.4)
    # ------------------------------------------------------------------
    def save_parameters(self, filename: str, deduplicate: bool = False) -> None:
        params = self._collect_params_with_prefix()
        from .. import ndarray as nd
        arg_dict = {}
        seen = {}
        for name, param in params.items():
            if param._data is None:
                raise RuntimeError(
                    f"Parameter '{param.name}' has not been initialized")
            if deduplicate and id(param) in seen:
                continue
            seen[id(param)] = name
            arg_dict[name] = param._check_and_get(param._data, None)
        # Non-finite weights checkpoint "successfully" and poison every
        # later restore — surface it at save time (one fused jitted
        # reduction, mx.fault.guards), where the step that broke them is
        # still identifiable. Warn-only: saving a diverged model for a
        # post-mortem is legitimate.
        from ..fault.guards import all_finite
        if not all_finite([a._data for a in arg_dict.values()]):
            import warnings
            warnings.warn(
                f"save_parameters({filename!r}): parameters contain "
                "non-finite values; the saved file will restore a broken "
                "model (a fault.StepGuard on the trainer catches this at "
                "the offending step)")
        nd.save(filename, arg_dict)

    def load_parameters(self, filename: str, ctx=None, allow_missing: bool = False,
                        ignore_extra: bool = False, cast_dtype: bool = False,
                        dtype_source: str = "current") -> None:
        from .. import ndarray as nd
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded.keys()):
            # legacy prefix-based file: route through ParameterDict.load
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix,
                cast_dtype=cast_dtype, dtype_source=dtype_source)
            return
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise AssertionError(
                        f"Parameter '{name}' is missing in file '{filename}'")
        for name, data in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise AssertionError(
                        f"Parameter '{name}' loaded from file '{filename}' is "
                        "not present in this block")
                continue
            params[name]._load_init(data, ctx or current_context(),
                                    cast_dtype=cast_dtype, dtype_source=dtype_source)

    save_params = save_parameters
    load_params = load_parameters

    # ------------------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs) -> None:
        """Print a per-layer summary of output shapes and param counts."""
        rows = []
        hooks = []

        def add_hook(block):
            def hook(blk, _, out):
                o = out[0] if isinstance(out, (list, tuple)) else out
                n_param = sum(
                    int(onp.prod(p.shape)) for p in blk.params.values()
                    if p.shape and all(s > 0 for s in p.shape))
                rows.append((type(blk).__name__, blk.name,
                             tuple(getattr(o, "shape", ())), n_param))
            hooks.append(block.register_forward_hook(hook))

        self.apply(add_hook)
        try:
            self(*inputs)
        finally:
            for h in hooks:
                h.detach()
        print(f"{'Layer (type)':<30}{'Output Shape':<24}{'Param #':<12}")
        print("-" * 66)
        total = 0
        for tname, name, shape, n in rows:
            print(f"{tname + ' (' + name + ')':<30}{str(shape):<24}{n:<12}")
            total += n
        print("-" * 66)
        print(f"Total params (incl. shared): {total}")

    def __repr__(self):
        s = f"{type(self).__name__}("
        for name, child in self._children.items():
            s += f"\n  ({name}): " + repr(child).replace("\n", "\n  ")
        return s + "\n)" if self._children else s + ")"


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks_dict):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._hooks = hooks_dict

    def detach(self):
        self._hooks.pop(self.id, None)


class HybridBlock(Block):
    """A Block whose forward is expressible as a pure function of its inputs
    and parameters — and therefore compilable (reference: hybridize() →
    CachedOp; here: → jax.jit)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags: Dict[str, Any] = {}
        self._jit_cache: Dict[Any, Callable] = {}
        self._cache_info: Dict[Any, dict] = {}
        self._warmed_up = False
        self._partition_if_dynamic = True

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, **kwargs) -> None:
        """Enable jit compilation of the forward (reference semantics:
        static_alloc/static_shape accepted; XLA buffer assignment subsumes
        both)."""
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape, **kwargs)
        self._clear_cached_op()
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def _clear_cached_op(self) -> None:
        self._jit_cache = {}
        self._cache_info = {}
        self._warmed_up = False
        # recompilation accounting restarts with the cache (mx.analysis)
        self.__dict__.pop("_compile_log", None)
        self.__dict__.pop("_compile_sigs", None)
        self.__dict__.pop("_recompile_warned", None)

    def infer_shape(self, *args) -> None:
        """Resolve deferred parameter shapes from input shapes. Layers with
        lazy in-channels override this (reference: generic symbolic shape
        inference; JAX has no unknown-dim inference, so it is per-layer)."""
        raise ValueError(
            f"Deferred initialization of parameters in {type(self).__name__} "
            "could not be resolved: override infer_shape() or give explicit "
            "in_units/in_channels.")

    def _get_ctx(self, flat_args) -> Context:
        for a in flat_args:
            if isinstance(a, NDArray):
                return a.context
        return current_context()

    def _fetch_params(self, ctx, args) -> Dict[str, NDArray]:
        try:
            return {name: p.data(ctx) for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_init_params(ctx, args)
            return {name: p.data(ctx) for name, p in self._reg_params.items()}

    def _deferred_init_params(self, ctx, args) -> None:
        self.infer_shape(*args)
        for p in self._reg_params.values():
            p._finish_deferred_init()

    # ------------------------------------------------------------------
    def forward(self, x, *args):
        if self._active and not _is_tracing() and isinstance(x, NDArray):
            return self._call_cached_op(x, *args)
        if isinstance(x, NDArray):
            if getattr(self, "_sg_graph", None) is not None and self._active:
                # optimize_for installed a partitioned graph: while
                # hybridized it IS the compute (running inside the cached-op
                # trace compiles it); hybridize(False) falls back to the
                # original eager forward, reference CachedOp semantics
                return self._forward_partitioned(x, *args)
            from .. import ndarray as F
            ctx = x.context
            params = self._fetch_params(ctx, (x,) + args)
            return self.hybrid_forward(F, x, *args, **params)
        # Symbol path (export / symbolic compose)
        from .. import symbol as F
        params = {name: p.var() for name, p in self._reg_params.items()}
        return self.hybrid_forward(F, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------------
    # the CachedOp: jit path
    # ------------------------------------------------------------------
    def _call_cached_op(self, *args):
        flat_args, fmt = _flatten_args(args)
        arr_args = [a for a in flat_args if isinstance(a, NDArray)]
        ctx = self._get_ctx(flat_args)

        if not self._warmed_up:
            # First call: run eagerly (finishes deferred init, discovers the
            # parameter set) — the reference's _build_cache moment.
            _TRACING.flag = True
            try:
                out = self.forward(*args)
            finally:
                _TRACING.flag = False
            self._cached_params = [
                p for _, p in sorted(self.collect_params().items())]
            self._warmed_up = True
            # export() works after THIS call already (one hybridized
            # forward, per the reference contract)
            self._last_sig = (_strip_arrays(args), len(arr_args),
                              [(tuple(a.shape), str(a._data.dtype))
                               for a in arr_args], ctx)
            return out

        params = self._cached_params
        param_vals = []
        for p in params:
            arr = p.data(ctx)
            param_vals.append(arr._data)
        training = autograd.is_training()
        key_val = random_mod.next_key(ctx)
        n_in = len(arr_args)
        # Remember the call signature so export() can re-trace an inference
        # version of this graph for the deploy artifact.
        self._last_sig = (_strip_arrays(args), n_in,
                          [(tuple(a.shape), str(a._data.dtype))
                           for a in arr_args], ctx)
        # Key must cover the arg *structure* (array count/nesting), not just
        # static leaf values — otherwise a call with a different number of
        # arrays would reuse a jit fn with a stale n_in/skeleton.
        cache_key = (training, n_in, repr(fmt), _static_key(flat_args))

        if cache_key not in self._jit_cache:
            info = {"out_fmt": None, "effects": []}
            self._cache_info[cache_key] = info
            block = self
            skeleton = _strip_arrays(args)

            def pure(key, *vals):
                ins, pvals = vals[:n_in], vals[n_in:]
                proxies = {}
                for p, v in zip(params, pvals):
                    proxies[id(p)] = NDArray(v, ctx=ctx)
                # rebuild args replacing NDArray slots with traced proxies
                it = iter(NDArray(v, ctx=ctx) for v in ins)
                rebuilt = _rebuild_args(skeleton, it)
                _TRACING.flag = True
                try:
                    with autograd.pause(train_mode=training), \
                            random_mod.trace_rng(key), \
                            _trace.TraceScope(proxies) as scope:
                        out = block.forward(*rebuilt)
                finally:
                    _TRACING.flag = False
                flat_out, out_fmt = _flatten_args(
                    out if isinstance(out, tuple) else (out,))
                info["out_fmt"] = out_fmt
                info["multi"] = isinstance(out, (tuple, list))
                info["effects"] = list(scope.effect_keys)
                prim = tuple(o._data if isinstance(o, NDArray) else o for o in flat_out)
                return prim + tuple(scope.effect_values)

            self._jit_cache[cache_key] = jax.jit(pure)

        # recompilation accounting: every distinct (static-key, input-aval)
        # signature is a fresh XLA compile — the block-level cache key alone
        # undercounts because jax.jit re-traces per shape/dtype inside one
        # entry. mx.analysis warns past a threshold (MX201).
        note_compile(self, (cache_key, tuple(self._last_sig[2])))

        jit_fn = self._jit_cache[cache_key]
        info = self._cache_info[cache_key]

        from ..ndarray.op import dispatch_op

        def tape_fn(*vals):
            return jit_fn(key_val, *vals)

        outs = dispatch_op(tape_fn, arr_args + list(params_data(params, ctx)),
                           {}, ctx, name=f"cached_op_{self._name}")
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        n_eff = len(info["effects"])
        prim = outs[: len(outs) - n_eff]
        effs = outs[len(outs) - n_eff:]
        for (p, ectx), val in zip(info["effects"], effs):
            p._deposit_aux(val._data, ectx if ectx is not None else ctx)
        flat_prim = list(prim)
        result = _regroup(flat_prim, info["out_fmt"])
        if not info["multi"]:
            return result[0]
        return tuple(result)

    # ------------------------------------------------------------------
    def _make_pure_infer(self, skeleton, n_in: int, ctx):
        """Build the inference-mode pure function over this block's cached
        graph: ``pure_infer(key_data, *inputs, *param_values) -> flat outs``
        traced with ``train_mode=False`` (dropout identity, BatchNorm on
        running stats). Returns ``(pure_infer, meta)`` — ``meta`` is filled
        with ``out_fmt``/``multi`` during tracing. Shared by
        :meth:`export` and the serving compiler
        (:class:`~incubator_mxnet_tpu.serve.CompiledModel`)."""
        impl = random_mod._impl()
        blk_params = self._cached_params
        meta: Dict[str, Any] = {}
        block = self

        def pure_infer(key_data, *vals):
            key = jax.random.wrap_key_data(key_data, impl=impl)
            ins, pvals = vals[:n_in], vals[n_in:]
            proxies = {id(p): NDArray(v, ctx=ctx)
                       for p, v in zip(blk_params, pvals)}
            it = iter(NDArray(v, ctx=ctx) for v in ins)
            rebuilt = _rebuild_args(skeleton, it)
            _TRACING.flag = True
            try:
                with autograd.pause(train_mode=False), \
                        random_mod.trace_rng(key), \
                        _trace.TraceScope(proxies):
                    out = block.forward(*rebuilt)
            finally:
                _TRACING.flag = False
            flat_out, out_fmt = _flatten_args(
                out if isinstance(out, tuple) else (out,))
            meta["out_fmt"] = out_fmt
            meta["multi"] = isinstance(out, (tuple, list))
            return tuple(o._data if isinstance(o, NDArray) else o
                         for o in flat_out)

        return pure_infer, meta

    def export(self, path: str, epoch: int = 0,
               platforms=None, signatures=None) -> Tuple[str, str]:
        """Serialize a self-contained deploy artifact (reference:
        HybridBlock.export → model-symbol.json + model-0000.params).

        TPU-native form: the inference forward is re-traced with
        ``train_mode=False`` and serialized as **StableHLO** via
        ``jax.export`` (`<path>-symbol.stablehlo`), alongside the dmlc
        ``.params`` weights and a JSON manifest that records the calling
        convention (input avals, parameter order, RNG key wire format,
        output structure). :meth:`SymbolBlock.imports` reconstructs a
        runnable block from these files WITHOUT the original Python class.

        Requires one prior hybridized call (the reference requires a forward
        before export for the same reason — shapes must be known).
        ``platforms``: optional list (e.g. ``["cpu", "tpu"]``) to make the
        artifact portable across backends; default = current backend only.

        ``signatures``: optional list of *additional-shape* input signatures
        to bake into the artifact — each entry is a list of ``(shape,
        dtype)`` pairs, one per array input. StableHLO graphs are
        fixed-shape, so a served model needs one graph per shape bucket;
        every listed signature is traced and serialized
        (``<path>-symbol.<i>.stablehlo``) and
        :meth:`SymbolBlock.forward` dispatches on the call's input shapes.
        Default: the recorded signature of the last hybridized call only.
        """
        import json

        params_file = f"{path}-{epoch:04d}.params"
        params = self._collect_params_with_prefix()
        from .. import ndarray as nd
        nd.save(params_file, {k: p._check_and_get(p._data, None)
                              for k, p in params.items() if p._data is not None})
        sym_file = f"{path}-symbol.json"
        if getattr(self, "_last_sig", None) is None:
            raise MXNetError(
                "export() needs a traced graph: call hybridize() and run one "
                "forward pass before exporting (reference behavior)")
        skeleton, n_in, in_avals, ctx = self._last_sig
        blk_params = self._cached_params
        name_by_id = {id(p): k for k, p in params.items()}
        param_order = [name_by_id[id(p)] for p in blk_params]
        impl = random_mod._impl()
        key_data_aval = jax.random.key_data(jax.random.key(0, impl=impl))

        # additional signatures ADD to the recorded one (deduped), so the
        # artifact can always replay the shape it was exported after
        sigs = [[(tuple(s), str(d)) for s, d in in_avals]]
        for sig in (signatures or []):
            norm = [(tuple(s), str(d)) for s, d in sig]
            if len(norm) != n_in:
                raise MXNetError(
                    f"export(signatures=...): each signature needs "
                    f"{n_in} (shape, dtype) input entries, got {len(norm)}")
            if norm not in sigs:
                sigs.append(norm)

        from jax import export as jax_export
        kwargs = {"platforms": tuple(platforms)} if platforms else {}
        sig_entries = []
        exported_platforms = None
        for i, sig in enumerate(sigs):
            pure_infer, meta = self._make_pure_infer(skeleton, n_in, ctx)
            args = [jax.ShapeDtypeStruct(key_data_aval.shape,
                                         key_data_aval.dtype)]
            args += [jax.ShapeDtypeStruct(s, jnp.dtype(d)) for s, d in sig]
            args += [jax.ShapeDtypeStruct(tuple(p.shape), jnp.dtype(p.dtype))
                     for p in blk_params]
            # one trace per exported artifact signature, not per request
            exported = jax_export.export(jax.jit(pure_infer), **kwargs)(*args)  # mxlint: disable=MX501
            hlo_file = (f"{path}-symbol.stablehlo" if i == 0
                        else f"{path}-symbol.{i}.stablehlo")
            with open(hlo_file, "wb") as f:
                f.write(exported.serialize())
            exported_platforms = list(exported.platforms)
            sig_entries.append({
                "in_avals": [[list(s), d] for s, d in sig],
                "stablehlo": hlo_file.rsplit("/", 1)[-1],
                "out_fmt": meta["out_fmt"],
                "multi": meta["multi"],
            })
        primary = sig_entries[0]
        arch = {
            "framework": "incubator_mxnet_tpu",
            "block": type(self).__name__,
            "name": self.name,
            "params": sorted(params.keys()),
            "param_order": param_order,
            "param_prefix_names": [p.name for p in blk_params],
            "n_inputs": n_in,
            "in_avals": primary["in_avals"],
            "key": {"shape": list(key_data_aval.shape),
                    "dtype": str(key_data_aval.dtype), "impl": impl},
            "out_fmt": primary["out_fmt"],
            "multi": primary["multi"],
            "stablehlo": primary["stablehlo"],
            "signatures": sig_entries,
            "platforms": exported_platforms,
        }
        with open(sym_file, "w") as f:
            json.dump(arch, f, indent=2)
        return sym_file, params_file

    def optimize_for(self, x, *args, backend=None, **kwargs):
        """Apply a subgraph backend, then compile (reference:
        HybridBlock.optimize_for over the subgraph property registry,
        src/operator/subgraph/). Two kinds of backend resolve here:
        block-rewrite passes (``gluon.block.register_subgraph_backend`` —
        the built-in ``"INT8"`` quantization swap), and graph-partitioning
        property backends (``mx.subgraph.register_backend`` — pattern-match
        and replace regions of the symbolically traced forward). XLA fusion
        itself needs no pass, so ``backend=None``/"XLA" is hybridize + one
        warm-up call."""
        if backend in (None, "XLA", "xla"):
            self._sg_graph = None  # revert any earlier partitioning
        else:
            from .. import subgraph as _subgraph
            if backend in _SUBGRAPH_BACKENDS:
                self._sg_graph = None  # block rewrite replaces partitioning
                _SUBGRAPH_BACKENDS[backend](self, x, *args, **kwargs)
            elif backend in _subgraph._BACKENDS:
                if kwargs:
                    raise MXNetError(
                        f"subgraph property backend {backend!r} takes no "
                        f"options; got {sorted(kwargs)}")
                self._install_partitioned_graph(backend, x, *args)
            else:
                raise MXNetError(
                    f"unknown subgraph backend {backend!r}; registered "
                    f"block passes: {sorted(_SUBGRAPH_BACKENDS)}, property "
                    f"backends: {_subgraph.list_backends()} (register with "
                    "gluon.block.register_subgraph_backend or "
                    "mx.subgraph.register_backend)")
        self.hybridize()
        return self(x, *args)

    def _install_partitioned_graph(self, backend, x, *args):
        """Trace the forward symbolically, partition it, and make the
        partitioned graph this block's compute (reference: the in-place
        CachedOp repartition done by HybridBlock.optimize_for)."""
        from .. import subgraph as _subgraph
        from .. import symbol as S
        bad = self._training_dependent_children()
        if bad:
            raise MXNetError(
                "property-backend partitioning traces the forward once in "
                "inference mode, which would bake training-time behavior "
                f"out of {bad}; blocks with training-dependent state "
                "(Dropout masks, BatchNorm running stats) are not supported "
                "here yet — use a block-rewrite backend "
                "(gluon.block.register_subgraph_backend) or plain "
                "hybridize() for this net")
        self(x, *args)  # finish deferred init so params have shapes
        data_vars = [S.Variable(f"data{i}") for i in range(1 + len(args))]
        out = self.forward(*data_vars)  # Symbol trace path
        if isinstance(out, (list, tuple)):
            out = S.Group(list(out))
        self._sg_graph = (_subgraph.partition(out, backend),
                          [v.name for v in data_vars])
        self._clear_cached_op()  # compiled pre-partition graphs are stale

    def _training_dependent_children(self) -> List[str]:
        """Names of descendant blocks whose forward depends on training
        mode or mutates running state — unsafe to freeze into a one-shot
        inference-mode symbolic trace."""
        from .nn import basic_layers as _bl
        kinds = (_bl.Dropout, _bl.BatchNorm)
        bad = []

        def walk(b):
            for child in b._children.values():
                if isinstance(child, kinds):
                    bad.append(f"{type(child).__name__}({child.name})")
                walk(child)

        walk(self)
        return bad

    def _forward_partitioned(self, x, *args):
        part, names = self._sg_graph
        ctx = x.context
        vals = dict(zip(names, (x,) + args))
        for pname, p in self.collect_params().items():
            vals[pname] = p.data(ctx)
        arg_names = part.list_arguments()
        missing = [a for a in arg_names if a not in vals]
        if missing:
            raise MXNetError(
                f"partitioned graph argument(s) {missing} not found among "
                "data inputs or parameters")
        from ..ndarray.op import dispatch_op
        from .. import symbol as S
        arrays = [vals[a] for a in arg_names]
        out = dispatch_op(S._compile_fn(part, arg_names), arrays, {}, ctx,
                          name=f"partitioned_{self._name}")
        multi = part._op == "_group"
        return list(out) if multi and isinstance(out, (list, tuple)) else out


#: subgraph-backend registry (reference: SubgraphBackendRegistry)
_SUBGRAPH_BACKENDS: Dict[str, Callable] = {}


def register_subgraph_backend(name: str, fn: Optional[Callable] = None):
    """Register a block-rewrite pass: ``fn(block, x, *args, **kwargs)``
    mutates the block tree in place before compilation. Usable as a
    decorator."""
    def _do(f):
        _SUBGRAPH_BACKENDS[name] = f
        return f
    return _do(fn) if fn is not None else _do


@register_subgraph_backend("INT8")
def _int8_backend(block, x, *args, calib_data=None, calib_mode="naive",
                  exclude_layers=(), **kwargs):
    from ..quantization import quantize_net
    quantize_net(block, calib_data=list(calib_data or [x]),
                 calib_mode=calib_mode, exclude_layers=exclude_layers)


def params_data(params, ctx):
    return [p.data(ctx) for p in params]


def _rebuild_args(args, it):
    def rec(a):
        if isinstance(a, NDArray) or isinstance(a, _ArrSlot):
            return next(it)
        if isinstance(a, (list, tuple)):
            return [rec(x) for x in a]
        return a

    return [rec(a) for a in args]


class SymbolBlock(HybridBlock):
    """A runnable Block reconstructed from an exported artifact (reference:
    gluon.SymbolBlock.imports over model-symbol.json + .params).

    TPU-native form: the compute graph is the serialized **StableHLO**
    written by :meth:`HybridBlock.export`; ``imports`` deserializes it with
    ``jax.export`` and replays it on call — the original Python Block class
    is NOT needed. Parameters load from the dmlc ``.params`` file and feed
    the compiled computation in the manifest's recorded order.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._outputs = outputs
        self._inputs = inputs
        self._exported = None
        self._sigs: List[dict] = []
        self._arch = outputs if isinstance(outputs, dict) else None
        self._param_arrays: Dict[str, NDArray] = {}

    @staticmethod
    def imports(symbol_file: str, input_names,
                param_file: Optional[str] = None, ctx=None) -> "SymbolBlock":
        import json
        import os
        with open(symbol_file) as f:
            arch = json.load(f)
        blk = SymbolBlock(arch, input_names)
        base = os.path.dirname(os.path.abspath(symbol_file))
        from jax import export as jax_export
        # multi-signature manifest (one fixed-shape StableHLO per shape
        # bucket); legacy single-graph manifests synthesize one entry
        entries = arch.get("signatures") or ([{
            "in_avals": arch["in_avals"], "stablehlo": arch.get("stablehlo"),
            "out_fmt": arch["out_fmt"], "multi": arch["multi"],
        }] if arch.get("stablehlo") else [])
        for ent in entries:
            with open(os.path.join(base, ent["stablehlo"]), "rb") as f:
                exported = jax_export.deserialize(bytearray(f.read()))
            blk._sigs.append({
                "exported": exported,
                "in_avals": [(tuple(s), str(d)) for s, d in ent["in_avals"]],
                "out_fmt": ent["out_fmt"], "multi": ent["multi"],
            })
        if blk._sigs:
            blk._exported = blk._sigs[0]["exported"]
        if param_file:
            from .. import ndarray as nd
            loaded = nd.load(param_file)
            if not isinstance(loaded, dict):
                raise MXNetError(f"{param_file}: expected a name->array dict")
            blk._param_arrays = loaded
            # surface them as real Parameters too (collect_params parity)
            for name, arr in loaded.items():
                p = blk.params.get(name, shape=arr.shape,
                                   dtype=str(arr._data.dtype))
                p._load_init(arr, ctx)
        return blk

    def signatures(self) -> List[Tuple[Tuple[tuple, str], ...]]:
        """The input (shape, dtype) signatures this artifact can run."""
        return [tuple(s["in_avals"]) for s in self._sigs]

    def _sig_for(self, ins) -> dict:
        shapes = [tuple(i.shape) for i in ins]
        dtypes = [str(i.dtype) for i in ins]
        shape_hits = [s for s in self._sigs
                      if [a[0] for a in s["in_avals"]] == shapes]
        for s in shape_hits:
            if [a[1] for a in s["in_avals"]] == dtypes:
                return s
        if shape_hits:  # shape match, dtype off — let XLA surface the cast
            return shape_hits[0]
        have = ", ".join(
            "(" + ", ".join(f"{a[0]}:{a[1]}" for a in s["in_avals"]) + ")"
            for s in self._sigs) or "<none>"
        raise MXNetError(
            f"no exported graph matches input shapes {shapes}; this "
            f"artifact was exported for: {have}. Re-export with "
            "signatures=[...] covering the needed shape buckets "
            "(serve.export_for_serving does this from a BucketTable).")

    def set_weights(self, mapping, ctx=None, allow_missing: bool = False,
                    ignore_extra: bool = False) -> int:
        """Swap parameter values in place (no recompile — shapes must
        match); returns how many parameters were updated. ``mapping`` maps
        manifest (dotted) names — or training-time prefix names, via the
        manifest's ``param_prefix_names`` — to NDArray/numpy values. This
        is the registry's version-swap path: weights from a newer
        ``fault.checkpoint`` land on a cold-loaded artifact without
        touching Python model code."""
        from .. import ndarray as nd
        arch = self._arch or {}
        order = arch.get("param_order", [])
        prefix_names = arch.get("param_prefix_names", [])
        by_prefix = dict(zip(prefix_names, order))
        known = set(order) | set(self._param_arrays)
        resolved: Dict[str, NDArray] = {}
        for name, arr in mapping.items():
            target = name if name in known else by_prefix.get(name)
            if target is None:
                if ignore_extra:
                    continue
                raise MXNetError(
                    f"set_weights: {name!r} is not a parameter of this "
                    f"artifact (known: {sorted(known)[:8]}...)")
            if not isinstance(arr, NDArray):
                arr = nd.array(onp.asarray(arr))
            old = self._param_arrays.get(target)
            if old is not None and tuple(old.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"set_weights: shape mismatch for {target!r}: artifact "
                    f"has {tuple(old.shape)}, new value is "
                    f"{tuple(arr.shape)}")
            resolved[target] = arr
        if not allow_missing:
            missing = [n for n in order if n not in resolved
                       and n not in self._param_arrays]
            if missing:
                raise MXNetError(f"set_weights: missing parameters "
                                 f"{missing}; pass allow_missing=True to "
                                 "keep current values")
        for name, arr in resolved.items():
            self._param_arrays[name] = arr
            p = self.params._params.get(name)
            if p is not None:
                p._load_init(arr, ctx)
            else:
                p = self.params.get(name, shape=arr.shape,
                                    dtype=str(arr._data.dtype))
                p._load_init(arr, ctx)
        return len(resolved)

    def load_parameters(self, filename: str, ctx=None,
                        allow_missing: bool = False,
                        ignore_extra: bool = False, cast_dtype: bool = False,
                        dtype_source: str = "current") -> None:
        """Refresh this artifact's weights from a ``.params`` file (the
        generic Block implementation walks ``_reg_params``, which an
        imported artifact does not have)."""
        from .. import ndarray as nd
        loaded = nd.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError(f"{filename}: expected a name->array dict")
        self.set_weights(loaded, ctx=ctx, allow_missing=allow_missing,
                         ignore_extra=ignore_extra)

    load_params = load_parameters

    def forward(self, *inputs):
        if not self._sigs:
            raise MXNetError(
                "this SymbolBlock was imported from a manifest without a "
                "StableHLO graph; re-export with HybridBlock.export() on "
                "this framework version")
        arch = self._arch
        n_in = arch["n_inputs"]
        if len(inputs) != n_in:
            raise MXNetError(f"expected {n_in} input array(s), "
                             f"got {len(inputs)}")
        ctx = inputs[0].context if isinstance(inputs[0], NDArray) \
            else current_context()
        ins = [i._data if isinstance(i, NDArray) else jnp.asarray(i)
               for i in inputs]
        sig = self._sig_for(ins)
        try:
            pvals = [self._param_arrays[n]._data for n in arch["param_order"]]
        except KeyError as e:
            raise MXNetError(f"missing parameter {e} — pass param_file to "
                             "imports()") from e
        key = jax.random.key_data(jax.random.key(0, impl=arch["key"]["impl"]))
        key = key.astype(jnp.dtype(arch["key"]["dtype"]))
        outs = sig["exported"].call(key, *ins, *pvals)
        flat = [NDArray(o, ctx=ctx) for o in outs]
        result = _regroup(flat, sig["out_fmt"])
        # sig["multi"] is a manifest bool, not a tracer
        return tuple(result) if sig["multi"] else result[0]  # mxlint: disable=MX204

    def hybrid_forward(self, F, x, *args, **kwargs):
        return self.forward(x, *args)

"""Estimator — the packaged Gluon fit loop.

Reference parity: ``python/mxnet/gluon/contrib/estimator/estimator.py`` —
``Estimator(net, loss, metrics, trainer).fit(train_data, val_data, epochs)``
with event handlers (epoch/batch begin/end).
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

from ... import autograd
from ... import metric as metric_mod
from ...ndarray import NDArray
from ..trainer import Trainer

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "CheckpointHandler",
           "EarlyStoppingHandler", "LoggingHandler"]


class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator, batch):
        pass


class BatchEnd:
    def batch_end(self, estimator, batch, loss):
        pass


def _resolve_monitor(handler, estimator, monitor):
    """Shared monitor→train-metric lookup with a one-shot warning when
    nothing matches (used by Checkpoint/EarlyStopping handlers)."""
    for m in estimator.train_metrics:
        if monitor in (None, m.name):
            return m.get()[1]
    if not getattr(handler, "_warned", False):
        handler._warned = True
        estimator.logger.warning(
            "%s: monitor %r matches no train metric (available: %s) — the "
            "handler is inactive", type(handler).__name__, monitor,
            [m.name for m in estimator.train_metrics])
    return None


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save parameters (+ trainer states) each epoch; with ``save_best`` keep
    only the best by a monitored metric (reference:
    estimator/event_handler.py CheckpointHandler)."""

    def __init__(self, model_dir: str, model_prefix: str = "model",
                 monitor: Optional[str] = None, mode: str = "min",
                 save_best: bool = False):
        import os
        os.makedirs(model_dir, exist_ok=True)
        if monitor is not None and not save_best:
            raise ValueError(
                "CheckpointHandler: monitor= only takes effect with "
                "save_best=True (every epoch is saved otherwise)")
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self._mode = mode
        self._better = (lambda a, b: a < b) if mode == "min" \
            else (lambda a, b: a > b)
        self.best = float("inf") if mode == "min" else -float("inf")
        self.saved: List[str] = []

    def train_begin(self, estimator):
        # handlers are reusable across fit() calls: monitoring state resets,
        # and `saved` reflects THIS run's checkpoints only
        self.best = float("inf") if self._mode == "min" else -float("inf")
        self._warned = False
        self.saved = []

    def epoch_end(self, estimator):
        import os
        stem = os.path.join(
            self.model_dir, f"{self.model_prefix}-{estimator.epoch:04d}")
        if self.save_best:
            cur = _resolve_monitor(self, estimator, self.monitor)
            if cur is None or not self._better(cur, self.best):
                return
            self.best = cur
            stem = os.path.join(self.model_dir, f"{self.model_prefix}-best")
        estimator.net.save_parameters(stem + ".params")
        estimator.trainer.save_states(stem + ".states")
        self.saved.append(stem + ".params")


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stop training when the monitored metric stops improving
    (reference: EarlyStoppingHandler — sets estimator.stop_training, which
    the fit loop checks at both batch and epoch boundaries)."""

    def __init__(self, monitor: Optional[str] = None, mode: str = "min",
                 patience: int = 0, min_delta: float = 0.0):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self._mode = mode
        self._better = (lambda a, b: a < b - min_delta) if mode == "min" \
            else (lambda a, b: a > b + min_delta)
        self.best = float("inf") if mode == "min" else -float("inf")
        self.wait = 0
        self.stopped_epoch: Optional[int] = None

    def train_begin(self, estimator):
        self.best = float("inf") if self._mode == "min" else -float("inf")
        self.wait = 0
        self.stopped_epoch = None
        self._warned = False

    def epoch_end(self, estimator):
        cur = _resolve_monitor(self, estimator, self.monitor)
        if cur is None:
            return
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait > self.patience:
            estimator.stop_training = True
            self.stopped_epoch = estimator.epoch


class LoggingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Per-interval batch/epoch logging (reference: LoggingHandler)."""

    def __init__(self, log_interval: int = 50):
        self.log_interval = log_interval
        self._batch = 0

    def train_begin(self, estimator):
        # an aborted fit() (stop_training mid-epoch) never reaches epoch_end,
        # so the counter must also reset here for handler reuse across fits
        self._batch = 0

    def batch_end(self, estimator, batch, loss):
        self._batch += 1
        if self._batch % self.log_interval == 0:
            estimator.logger.info(
                "Epoch[%d] Batch[%d] loss=%.4f %s", estimator.epoch,
                self._batch, float(loss.asnumpy()),
                " ".join(f"{m.name}={m.get()[1]:.4f}"
                         for m in estimator.train_metrics))

    def epoch_end(self, estimator):
        self._batch = 0


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer: Optional[Trainer] = None,
                 context=None, logger=None):
        self.net = net
        self.loss = loss
        import copy
        mets = train_metrics or [metric_mod.Accuracy()]
        self.train_metrics = mets if isinstance(mets, (list, tuple)) else [mets]
        # validation gets its OWN metric instances (reference keeps
        # val_metrics separate) so evaluate() never clobbers the training
        # values the epoch_end handlers monitor
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.logger = logger or logging.getLogger("estimator")
        self.epoch = 0
        self.stop_training = False  # handlers may set (EarlyStoppingHandler)

    def _batch_fn(self, batch):
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        label = batch.label[0] if hasattr(batch, "label") else batch[1]
        return data, label

    def evaluate(self, val_data, metrics=None):
        metrics = metrics or self.val_metrics
        for m in metrics:
            m.reset()
        val_data.reset()
        for batch in val_data:
            data, label = self._batch_fn(batch)
            with autograd.predict_mode():
                out = self.net(data)
            for m in metrics:
                m.update(label, out)
        return [(m.name, m.get()[1]) for m in metrics]

    def fit(self, train_data, val_data=None, epochs: int = 1,
            event_handlers: Sequence = (), batches: Optional[int] = None):
        handlers = list(event_handlers)
        self.stop_training = False
        for h in handlers:
            if isinstance(h, TrainBegin):
                h.train_begin(self)
        for epoch in range(epochs):
            if self.stop_training:
                break
            self.epoch = epoch
            for m in self.train_metrics:
                m.reset()
            for h in handlers:
                if isinstance(h, EpochBegin):
                    h.epoch_begin(self)
            train_data.reset()
            # reference-parity epoch speedometer (predates mx.telemetry);
            # the trainer underneath publishes train.step to the bus
            t0 = time.time()  # mxlint: disable=MX601
            n = 0
            for batch in train_data:
                if batches is not None and n >= batches:
                    break
                if self.stop_training:   # a BatchEnd guard (e.g. NaN stop)
                    break
                for h in handlers:
                    if isinstance(h, BatchBegin):
                        h.batch_begin(self, batch)
                data, label = self._batch_fn(batch)
                bs = data.shape[0]
                with autograd.record():
                    out = self.net(data)
                    loss = self.loss(out, label).mean()
                loss.backward()
                self.trainer.step(bs)
                for m in self.train_metrics:
                    m.update(label, out)
                for h in handlers:
                    if isinstance(h, BatchEnd):
                        h.batch_end(self, batch, loss)
                n += 1
            if self.stop_training:
                # set by a batch handler this epoch (the top-of-epoch check
                # broke out otherwise) — even on the final/capped batch, where
                # the in-loop check is never re-reached.  Partial-epoch metrics
                # must not reach epoch_end handlers: a CheckpointHandler would
                # save the diverged weights as a healthy per-epoch checkpoint
                break
            msg = f"Epoch[{epoch}] {time.time() - t0:.1f}s " + " ".join(
                f"train-{m.name}={m.get()[1]:.4f}" for m in self.train_metrics)
            if val_data is not None:
                msg += " " + " ".join(
                    f"val-{name}={v:.4f}"
                    for name, v in self.evaluate(val_data))
            self.logger.info(msg)
            for h in handlers:
                if isinstance(h, EpochEnd):
                    h.epoch_end(self)
        for h in handlers:
            if isinstance(h, TrainEnd):
                h.train_end(self)
        return self

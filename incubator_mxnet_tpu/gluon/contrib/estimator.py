"""Estimator — the packaged Gluon fit loop.

Reference parity: ``python/mxnet/gluon/contrib/estimator/estimator.py`` —
``Estimator(net, loss, metrics, trainer).fit(train_data, val_data, epochs)``
with event handlers (epoch/batch begin/end).
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

from ... import autograd
from ... import metric as metric_mod
from ...ndarray import NDArray
from ..trainer import Trainer

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd"]


class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator, batch):
        pass


class BatchEnd:
    def batch_end(self, estimator, batch, loss):
        pass


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer: Optional[Trainer] = None,
                 context=None, logger=None):
        self.net = net
        self.loss = loss
        mets = train_metrics or [metric_mod.Accuracy()]
        self.train_metrics = mets if isinstance(mets, (list, tuple)) else [mets]
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.logger = logger or logging.getLogger("estimator")
        self.epoch = 0

    def _batch_fn(self, batch):
        data = batch.data[0] if hasattr(batch, "data") else batch[0]
        label = batch.label[0] if hasattr(batch, "label") else batch[1]
        return data, label

    def evaluate(self, val_data, metrics=None):
        metrics = metrics or self.train_metrics
        for m in metrics:
            m.reset()
        val_data.reset()
        for batch in val_data:
            data, label = self._batch_fn(batch)
            with autograd.predict_mode():
                out = self.net(data)
            for m in metrics:
                m.update(label, out)
        return [(m.name, m.get()[1]) for m in metrics]

    def fit(self, train_data, val_data=None, epochs: int = 1,
            event_handlers: Sequence = (), batches: Optional[int] = None):
        handlers = list(event_handlers)
        for h in handlers:
            if isinstance(h, TrainBegin):
                h.train_begin(self)
        for epoch in range(epochs):
            self.epoch = epoch
            for m in self.train_metrics:
                m.reset()
            for h in handlers:
                if isinstance(h, EpochBegin):
                    h.epoch_begin(self)
            train_data.reset()
            t0 = time.time()
            n = 0
            for batch in train_data:
                if batches is not None and n >= batches:
                    break
                for h in handlers:
                    if isinstance(h, BatchBegin):
                        h.batch_begin(self, batch)
                data, label = self._batch_fn(batch)
                bs = data.shape[0]
                with autograd.record():
                    out = self.net(data)
                    loss = self.loss(out, label).mean()
                loss.backward()
                self.trainer.step(bs)
                for m in self.train_metrics:
                    m.update(label, out)
                for h in handlers:
                    if isinstance(h, BatchEnd):
                        h.batch_end(self, batch, loss)
                n += 1
            msg = f"Epoch[{epoch}] {time.time() - t0:.1f}s " + " ".join(
                f"train-{m.name}={m.get()[1]:.4f}" for m in self.train_metrics)
            if val_data is not None:
                msg += " " + " ".join(
                    f"val-{name}={v:.4f}"
                    for name, v in self.evaluate(val_data))
            self.logger.info(msg)
            for h in handlers:
                if isinstance(h, EpochEnd):
                    h.epoch_end(self)
        for h in handlers:
            if isinstance(h, TrainEnd):
                h.train_end(self)
        return self

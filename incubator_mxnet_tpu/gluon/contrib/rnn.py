"""Experimental recurrent cells.

Reference counterpart: ``python/mxnet/gluon/contrib/rnn/rnn_cell.py``
(``VariationalDropoutCell``, ``LSTMPCell``) and ``conv_rnn_cell.py``
(``Conv1D/2D/3DRNNCell``, ``Conv1D/2D/3DLSTMCell``, ``Conv1D/2D/3DGRUCell``).
Each step is a HybridBlock like the core cells, so a full unroll compiles
into one XLA program; the convolutional gates lower to MXU-tiled
``lax.conv_general_dilated`` calls through the registered Convolution op.
"""
from __future__ import annotations

from typing import Sequence, Tuple

from ...base import MXNetError
from ..rnn.rnn_cell import HybridRecurrentCell, ModifierCell

__all__ = ["VariationalDropoutCell", "LSTMPCell",
           "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (recurrent) dropout: ONE mask per unroll for inputs,
    states, and outputs, reused across time steps (Gal & Ghahramani) —
    reference ``contrib.rnn.VariationalDropoutCell``. Masks are drawn
    lazily on the first step after ``reset()``."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        self._mask_i = self._mask_s = self._mask_o = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._mask_i = self._mask_s = self._mask_o = None

    def _mask(self, F, like, p):
        from ... import random as random_mod
        key = random_mod.next_key(getattr(like, "context", None))
        # inverted-dropout mask (0 or 1/(1-p)) frozen for the whole unroll
        return F.Dropout(F.ones_like(like), p=p, training=True, key=key)

    def hybrid_forward(self, F, inputs, states):
        from ... import autograd
        if autograd.is_training():
            if self._drop_inputs:
                if self._mask_i is None:
                    self._mask_i = self._mask(F, inputs, self._drop_inputs)
                inputs = inputs * self._mask_i
            if self._drop_states:
                if self._mask_s is None:
                    self._mask_s = self._mask(F, states[0], self._drop_states)
                states = [states[0] * self._mask_s] + list(states[1:])
        output, states = self.base_cell(inputs, states)
        if autograd.is_training() and self._drop_outputs:
            if self._mask_o is None:
                self._mask_o = self._mask(F, output, self._drop_outputs)
            output = output * self._mask_o
        return output, states


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a hidden-state projection (reference:
    ``contrib.rnn.LSTMPCell``, the LSTMP of Sak et al.): the recurrent /
    output state is ``r = W_r·h`` with ``r`` of ``projection_size``, cutting
    the recurrent matmul from H×H to H×P."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sg = F.split(gates, num_outputs=4, axis=-1)
        in_gate = F.Activation(sg[0], act_type="sigmoid")
        forget_gate = F.Activation(sg[1], act_type="sigmoid")
        in_transform = F.Activation(sg[2], act_type="tanh")
        out_gate = F.Activation(sg[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        hidden = out_gate * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]


# ---------------------------------------------------------------------------
# Convolutional recurrent cells
# ---------------------------------------------------------------------------

def _tup(v, n: int) -> Tuple[int, ...]:
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    if len(t) != n:
        raise MXNetError(f"expected {n}-d value, got {t}")
    return t


class _ConvRNNCellBase(HybridRecurrentCell):
    """Shared machinery: gate pre-activations are convolutions of the input
    (i2h) and the recurrent state (h2h); spatial dims must be preserved, so
    strides are 1 and paddings default to kernel//2 (odd kernels)."""

    _num_gates = 1

    def __init__(self, input_shape: Sequence[int], hidden_channels: int,
                 i2h_kernel, h2h_kernel, i2h_pad=None, dims: int = 2,
                 conv_layout: str = None, activation: str = "tanh",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._dims = dims
        expected = ("NCW", "NCHW", "NCDHW")[dims - 1]
        if conv_layout is not None and conv_layout != expected:
            raise MXNetError(
                f"conv_layout {conv_layout!r} unsupported: only the channel-"
                f"first {expected} layout lowers here (reference NHWC "
                "layouts are a GPU-era option)")
        self._input_shape = tuple(input_shape)  # (C_in, *spatial)
        if len(self._input_shape) != dims + 1:
            raise MXNetError(
                f"input_shape must be (channels, {dims} spatial dims), got "
                f"{self._input_shape}")
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError(
                    f"h2h_kernel must be odd to preserve spatial dims, got "
                    f"{self._h2h_kernel}")
        self._i2h_pad = _tup(i2h_pad, dims) if i2h_pad is not None \
            else tuple(k // 2 for k in self._i2h_kernel)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        ng = self._num_gates
        cin = self._input_shape[0]
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(ng * hidden_channels, cin) + self._i2h_kernel,
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ng * hidden_channels, hidden_channels) + self._h2h_kernel,
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_channels,), init="zeros",
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_channels,), init="zeros",
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._input_shape[1:]
        return [{"shape": shape, "__layout__": "NC" + "DHW"[3 - self._dims:]}]

    def _convs(self, F, inputs, state, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=ng * self._hidden_channels)
        h2h = F.Convolution(state, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=ng * self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_ConvRNNCellBase):
    _num_gates = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class _ConvLSTMCell(_ConvRNNCellBase):
    _num_gates = 4

    def _alias(self):
        return "conv_lstm"

    def state_info(self, batch_size=0):
        info = super().state_info(batch_size)
        return info + [dict(info[0])]       # (h, c)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gates = i2h + h2h
        sg = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.Activation(sg[0], act_type="sigmoid")
        forget_gate = F.Activation(sg[1], act_type="sigmoid")
        in_transform = F.Activation(sg[2], act_type=self._activation)
        out_gate = F.Activation(sg[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_ConvRNNCellBase):
    _num_gates = 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_t = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_t = F.split(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        cand = F.Activation(i2h_t + reset * h2h_t,
                            act_type=self._activation)
        next_h = (1.0 - update) * cand + update * states[0]
        return next_h, [next_h]


def _make(base, dims, doc):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=None, activation="tanh",
                 prefix=None, params=None):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, i2h_pad=i2h_pad, dims=dims,
                      activation=activation, prefix=prefix, params=params)
    cls = type(f"Conv{dims}D{doc}Cell", (base,), {"__init__": __init__})
    cls.__doc__ = (f"{dims}-D convolutional {doc} cell (reference: "
                   f"contrib.rnn.Conv{dims}D{doc}Cell). input_shape = "
                   f"(channels, {dims} spatial dims).")
    return cls


Conv1DRNNCell = _make(_ConvRNNCell, 1, "RNN")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "RNN")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "RNN")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "LSTM")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "LSTM")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "LSTM")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "GRU")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "GRU")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "GRU")

"""gluon.contrib (reference: python/mxnet/gluon/contrib — SURVEY §2.8):
SyncBatchNorm, the estimator fit loop, and misc experimental blocks."""
from ..nn.basic_layers import SyncBatchNorm  # noqa: F401
from . import estimator  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from .estimator import Estimator  # noqa: F401

"""Experimental gluon layers.

Reference counterpart: ``python/mxnet/gluon/contrib/nn/basic_layers.py`` —
``Concurrent``/``HybridConcurrent`` (parallel branches concatenated on an
axis, the Inception building block), ``Identity``, and ``SparseEmbedding``.
On TPU ``SparseEmbedding`` is the plain dense-gradient Embedding (row_sparse
gradients are a parameter-server-era optimization; SURVEY §7 scopes sparse
to a dense facade) — the class exists so reference model code imports
unchanged.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.basic_layers import Embedding

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding"]


class HybridConcurrent(HybridBlock):
    """Feed the same input to every child, concat outputs along ``axis``.

    Use ``.add(block)`` like a Sequential::

        net = HybridConcurrent(axis=1)
        net.add(branch_a)
        net.add(branch_b)
    """

    def __init__(self, axis: int = -1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, block) -> None:
        self.register_child(block, f"branch{len(self._children)}")

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._children.values()],
                        dim=self.axis)

    def __len__(self):
        return len(self._children)


class Concurrent(HybridConcurrent):
    """Imperative alias (the hybrid version runs eagerly too)."""


class Identity(HybridBlock):
    """Pass-through block (reference: contrib.nn.Identity) — useful as a
    no-op branch in Concurrent/residual constructions."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Reference: contrib.nn.SparseEmbedding (row_sparse gradient
    embedding). TPU-native: dense gradients (XLA scatter-add); same call
    signature, documented divergence."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=False, **kwargs)

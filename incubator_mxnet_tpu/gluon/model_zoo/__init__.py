"""Model zoo (reference: python/mxnet/gluon/model_zoo — SURVEY §2.8)."""
from . import vision  # noqa: F401
from .vision import get_model  # noqa: F401

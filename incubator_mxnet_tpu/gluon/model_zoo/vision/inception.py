"""Inception V3 (reference: python/mxnet/gluon/model_zoo/vision/inception.py).

Same block taxonomy as the reference (A: 35x35, B: grid reduction, C: 17x17
factorized 7x7 convs, D: reduction, E: 8x8 with split 3x3 branches), NCHW,
input 299x299. Every branch is Conv+BN+ReLU so the whole network lowers to
MXU-tiled convolutions under one jit.
"""
from ...block import HybridBlock
from ...contrib.nn import HybridConcurrent
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _conv(out_channels, kernel, stride=1, padding=0):
    seq = nn.HybridSequential(prefix="")
    seq.add(nn.Conv2D(out_channels, kernel, stride, padding, use_bias=False))
    seq.add(nn.BatchNorm(epsilon=0.001))
    seq.add(nn.Activation("relu"))
    return seq


def _branch(*convs):
    seq = nn.HybridSequential(prefix="")
    for args in convs:
        if args[0] == "pool_avg":
            seq.add(nn.AvgPool2D(3, 1, 1))
        elif args[0] == "pool_max":
            seq.add(nn.MaxPool2D(3, 2))
        else:
            seq.add(_conv(*args))
    return seq


def _Concurrent():
    return HybridConcurrent(axis=1)


def _inception_a(pool_features):
    out = _Concurrent()
    out.add(_branch((64, 1)))
    out.add(_branch((48, 1), (64, 5, 1, 2)))
    out.add(_branch((64, 1), (96, 3, 1, 1), (96, 3, 1, 1)))
    out.add(_branch(("pool_avg",), (pool_features, 1)))
    return out


def _inception_b():
    out = _Concurrent()
    out.add(_branch((384, 3, 2)))
    out.add(_branch((64, 1), (96, 3, 1, 1), (96, 3, 2)))
    out.add(_branch(("pool_max",)))
    return out


def _inception_c(channels_7x7):
    c = channels_7x7
    out = _Concurrent()
    out.add(_branch((192, 1)))
    out.add(_branch((c, 1), (c, (1, 7), 1, (0, 3)), (192, (7, 1), 1, (3, 0))))
    out.add(_branch((c, 1), (c, (7, 1), 1, (3, 0)), (c, (1, 7), 1, (0, 3)),
                    (c, (7, 1), 1, (3, 0)), (192, (1, 7), 1, (0, 3))))
    out.add(_branch(("pool_avg",), (192, 1)))
    return out


def _inception_d():
    out = _Concurrent()
    out.add(_branch((192, 1), (320, 3, 2)))
    out.add(_branch((192, 1), (192, (1, 7), 1, (0, 3)),
                    (192, (7, 1), 1, (3, 0)), (192, 3, 2)))
    out.add(_branch(("pool_max",)))
    return out


class _SplitBranch(HybridBlock):
    """stem -> two parallel heads, concatenated (the E-block 3x3 split)."""

    def __init__(self, stem, heads, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.stem = stem
            self.heads = _Concurrent()
            for h in heads:
                self.heads.add(h)

    def hybrid_forward(self, F, x):
        return self.heads(self.stem(x))


def _inception_e():
    out = _Concurrent()
    out.add(_branch((320, 1)))
    out.add(_SplitBranch(
        _branch((384, 1)),
        [_branch((384, (1, 3), 1, (0, 1))), _branch((384, (3, 1), 1, (1, 0)))]))
    out.add(_SplitBranch(
        _branch((448, 1), (384, 3, 1, 1)),
        [_branch((384, (1, 3), 1, (0, 1))), _branch((384, (3, 1), 1, (1, 0)))]))
    out.add(_branch(("pool_avg",), (192, 1)))
    return out


class Inception3(HybridBlock):
    """Inception V3, 299x299 input (reference: model_zoo Inception3)."""

    def __init__(self, classes=1000, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_conv(32, 3, 2))
            self.features.add(_conv(32, 3))
            self.features.add(_conv(64, 3, 1, 1))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(_conv(80, 1))
            self.features.add(_conv(192, 3))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(_inception_a(32))
            self.features.add(_inception_a(64))
            self.features.add(_inception_a(64))
            self.features.add(_inception_b())
            self.features.add(_inception_c(128))
            self.features.add(_inception_c(160))
            self.features.add(_inception_c(160))
            self.features.add(_inception_c(192))
            self.features.add(_inception_d())
            self.features.add(_inception_e())
            self.features.add(_inception_e())
            self.features.add(nn.AvgPool2D(8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


def inception_v3(pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights need network access")
    return Inception3(**kw)

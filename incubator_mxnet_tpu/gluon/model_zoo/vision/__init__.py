"""Vision model zoo.

Reference parity: ``python/mxnet/gluon/model_zoo/vision/`` — resnet
(v1/v2, 18–152), vgg (11–19 ±BN), alexnet, squeezenet, densenet,
mobilenet (v1/v2), accessible by name through :func:`get_model`
(GluonCV's ResNet-50 recipe in BASELINE.json builds on these).

All HybridBlocks in NCHW; ``hybridize()`` compiles each to one XLA
computation whose convs tile onto the MXU. Pretrained-weight download needs
network access — load converted weights via ``load_parameters`` instead.
"""
from __future__ import annotations

from ...block import HybridBlock  # noqa: F401  (re-export convenience)
from .resnet import (  # noqa: F401
    ResNetV1, ResNetV2, resnet18_v1, resnet34_v1, resnet50_v1, resnet101_v1,
    resnet152_v1, resnet18_v2, resnet34_v2, resnet50_v2, resnet101_v2,
    resnet152_v2, get_resnet, resnet_sharding_rules,
)
from .alexnet import AlexNet, alexnet  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19, vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn  # noqa: F401
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa: F401
from .densenet import DenseNet, densenet121, densenet161, densenet169, densenet201  # noqa: F401
from .inception import Inception3, inception_v3  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNet, MobileNetV2, mobilenet1_0, mobilenet0_75, mobilenet0_5,
    mobilenet0_25, mobilenet_v2_1_0, mobilenet_v2_0_75, mobilenet_v2_0_5,
    mobilenet_v2_0_25,
)

_MODELS = {}


def _register_models():
    import sys
    mod = sys.modules[__name__]
    for name in ["resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
                 "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
                 "resnet101_v2", "resnet152_v2", "alexnet", "vgg11", "vgg13",
                 "vgg16", "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn",
                 "vgg19_bn", "squeezenet1.0", "squeezenet1.1", "densenet121",
                 "densenet161", "densenet169", "densenet201", "inceptionv3",
                 "mobilenet1.0",
                 "mobilenet0.75", "mobilenet0.5", "mobilenet0.25",
                 "mobilenetv2_1.0", "mobilenetv2_0.75", "mobilenetv2_0.5",
                 "mobilenetv2_0.25"]:
        attr = name.replace(".", "_").replace("mobilenetv2", "mobilenet_v2")
        attr = attr.replace("inceptionv3", "inception_v3")
        _MODELS[name] = getattr(mod, attr)


_register_models()


def get_model(name: str, **kwargs):
    """Name-based constructor (reference: model_zoo.vision.get_model)."""
    name = name.lower()
    if name not in _MODELS:
        raise ValueError(
            f"Model {name!r} is not in the zoo. Available: {sorted(_MODELS)}")
    return _MODELS[name](**kwargs)

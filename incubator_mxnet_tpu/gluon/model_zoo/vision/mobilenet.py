"""MobileNet v1/v2 (reference: gluon/model_zoo/vision/mobilenet.py).

Depthwise convs use grouped Convolution (num_group=channels) — XLA lowers
these as feature-group convolutions on the MXU.
"""
from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25"]


def _add_conv(out, channels, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        out.add(nn.Activation("relu"))  # relu6 ≈ relu for parity purposes


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1)
            dw_channels = [int(x * multiplier) for x in
                           [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
            channels = [int(x * multiplier) for x in
                        [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
            strides = [1, 2] * 3 + [1] * 5 + [2, 1]
            for dwc, c, s in zip(dw_channels, channels, strides):
                _add_conv_dw(self.features, dwc, c, s)
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class _LinearBottleneck(HybridBlock):
    def __init__(self, in_channels, channels, t, stride, **kw):
        super().__init__(**kw)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = nn.HybridSequential()
            _add_conv(self.out, in_channels * t, relu6=True)
            _add_conv(self.out, in_channels * t, 3, stride, 1,
                      num_group=in_channels * t, relu6=True)
            _add_conv(self.out, channels, active=False)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1, relu6=True)
            in_c = [int(multiplier * x) for x in
                    [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3
                    + [160] * 3]
            channels = [int(multiplier * x) for x in
                        [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3
                        + [160] * 3 + [320]]
            ts = [1] + [6] * 16
            strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3
            for ic, c, t, s in zip(in_c, channels, ts, strides):
                self.features.add(_LinearBottleneck(ic, c, t, s))
            last = int(1280 * multiplier) if multiplier > 1.0 else 1280
            _add_conv(self.features, last, relu6=True)
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            self.output.add(nn.Conv2D(classes, 1, use_bias=False))
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _mk(cls, multiplier, pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights need network access")
    return cls(multiplier, **kw)


def mobilenet1_0(**kw):
    return _mk(MobileNet, 1.0, **kw)


def mobilenet0_75(**kw):
    return _mk(MobileNet, 0.75, **kw)


def mobilenet0_5(**kw):
    return _mk(MobileNet, 0.5, **kw)


def mobilenet0_25(**kw):
    return _mk(MobileNet, 0.25, **kw)


def mobilenet_v2_1_0(**kw):
    return _mk(MobileNetV2, 1.0, **kw)


def mobilenet_v2_0_75(**kw):
    return _mk(MobileNetV2, 0.75, **kw)


def mobilenet_v2_0_5(**kw):
    return _mk(MobileNetV2, 0.5, **kw)


def mobilenet_v2_0_25(**kw):
    return _mk(MobileNetV2, 0.25, **kw)

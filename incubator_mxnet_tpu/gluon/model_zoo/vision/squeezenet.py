"""SqueezeNet 1.0/1.1 (reference: gluon/model_zoo/vision/squeezenet.py)."""
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
            self.left = nn.Conv2D(expand1x1, 1, activation="relu")
            self.right = nn.Conv2D(expand3x3, 3, padding=1, activation="relu")

    def hybrid_forward(self, F, x):
        x = self.squeeze(x)
        return F.concat(self.left(x), self.right(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(16, 64), (16, 64), (32, 128)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(32, 128), (48, 192), (48, 192), (64, 256)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                self.features.add(_Fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(16, 64), (16, 64)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(32, 128), (32, 128)]:
                    self.features.add(_Fire(s, e, e))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for s, e in [(48, 192), (48, 192), (64, 256), (64, 256)]:
                    self.features.add(_Fire(s, e, e))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="output_")
            self.output.add(nn.Conv2D(classes, 1, activation="relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights need network access")
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights need network access")
    return SqueezeNet("1.1", **kw)

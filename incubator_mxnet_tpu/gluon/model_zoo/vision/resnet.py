"""ResNet v1/v2 (reference: gluon/model_zoo/vision/resnet.py; the
BASELINE.json ResNet-50 recipe's backbone).

v1 = post-activation bottleneck/basic blocks with downsample shortcuts;
v2 = pre-activation (BN-relu-conv). Layer/channels tables match the
reference so converted parameter files line up name-for-name.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "get_resnet", "resnet_sharding_rules",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _conv3x3(channels, stride, in_channels, prefix):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels, prefix=prefix)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(_conv3x3(channels, stride, in_channels, "conv1_"))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(_conv3x3(channels, 1, channels, "conv2_"))
            self.body.add(nn.BatchNorm())
            if downsample:
                self.downsample = nn.HybridSequential(prefix="down_")
                self.downsample.add(nn.Conv2D(channels, 1, strides=stride,
                                              use_bias=False,
                                              in_channels=in_channels))
                self.downsample.add(nn.BatchNorm())
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x if self.downsample is None else self.downsample(x)
        return F.relu(self.body(x) + residual)


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(nn.Conv2D(channels // 4, 1, strides=stride,
                                    use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(_conv3x3(channels // 4, 1, channels // 4, "conv2_"))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(channels, 1, use_bias=False))
            self.body.add(nn.BatchNorm())
            if downsample:
                self.downsample = nn.HybridSequential(prefix="down_")
                self.downsample.add(nn.Conv2D(channels, 1, strides=stride,
                                              use_bias=False,
                                              in_channels=in_channels))
                self.downsample.add(nn.BatchNorm())
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x if self.downsample is None else self.downsample(x)
        return F.relu(self.body(x) + residual)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.bn1 = nn.BatchNorm()
            self.conv1 = _conv3x3(channels, stride, in_channels, "conv1_")
            self.bn2 = nn.BatchNorm()
            self.conv2 = _conv3x3(channels, 1, channels, "conv2_")
            self.downsample = nn.Conv2D(channels, 1, strides=stride,
                                        use_bias=False,
                                        in_channels=in_channels,
                                        prefix="down_") if downsample else None

    def hybrid_forward(self, F, x):
        residual = x
        x = F.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.conv2(F.relu(self.bn2(x)))
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.bn1 = nn.BatchNorm()
            self.conv1 = nn.Conv2D(channels // 4, 1, use_bias=False)
            self.bn2 = nn.BatchNorm()
            self.conv2 = _conv3x3(channels // 4, stride, channels // 4, "conv2_")
            self.bn3 = nn.BatchNorm()
            self.conv3 = nn.Conv2D(channels, 1, use_bias=False)
            self.downsample = nn.Conv2D(channels, 1, strides=stride,
                                        use_bias=False,
                                        in_channels=in_channels,
                                        prefix="down_") if downsample else None

    def hybrid_forward(self, F, x):
        residual = x
        x = F.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.conv2(F.relu(self.bn2(x)))
        x = self.conv3(F.relu(self.bn3(x)))
        return x + residual


#: num_layers -> (block_type, layers-per-stage, stage channels)
RESNET_SPEC = {
    18: ("basic", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, "conv0_"))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                stage = nn.HybridSequential(prefix=f"stage{i + 1}_")
                with stage.name_scope():
                    stage.add(block(channels[i + 1], stride,
                                    channels[i + 1] != channels[i],
                                    in_channels=channels[i]))
                    for _ in range(num_layer - 1):
                        stage.add(block(channels[i + 1], 1, False,
                                        in_channels=channels[i + 1]))
                self.features.add(stage)
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, "conv0_"))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                stage = nn.HybridSequential(prefix=f"stage{i + 1}_")
                with stage.name_scope():
                    stage.add(block(channels[i + 1], stride,
                                    channels[i + 1] != in_channels,
                                    in_channels=in_channels))
                    for _ in range(num_layer - 1):
                        stage.add(block(channels[i + 1], 1, False,
                                        in_channels=channels[i + 1]))
                self.features.add(stage)
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


_BLOCKS = {1: {"basic": BasicBlockV1, "bottle": BottleneckV1},
           2: {"basic": BasicBlockV2, "bottle": BottleneckV2}}
_NETS = {1: ResNetV1, 2: ResNetV2}


def get_resnet(version: int, num_layers: int, pretrained: bool = False,
               **kwargs):
    if pretrained:
        raise ValueError("pretrained weights need network access; use "
                         "load_parameters with a converted .params file")
    btype, layers, channels = RESNET_SPEC[num_layers]
    return _NETS[version](_BLOCKS[version][btype], layers, channels, **kwargs)


def resnet_sharding_rules(extra=()):
    """Channel-parallel TP rules for ShardedTrainer: conv weights are
    (O, I, kh, kw); split output channels, replicate BN."""
    from ....parallel.sharding import P, ShardingRules
    return ShardingRules(list(extra) + [
        (r".*conv.*weight", P("tp", None, None, None)),
        (r".*dense.*weight", P(None, "tp")),
    ])


def resnet18_v1(**kw):
    return get_resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return get_resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return get_resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return get_resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return get_resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return get_resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return get_resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return get_resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return get_resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return get_resnet(2, 152, **kw)

"""VGG 11/13/16/19 ±BN (reference: gluon/model_zoo/vision/vgg.py)."""
from ...block import HybridBlock
from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn"]

_SPEC = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for i, num in enumerate(layers):
                for _ in range(num):
                    self.features.add(nn.Conv2D(filters[i], 3, padding=1))
                    if batch_norm:
                        self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _vgg(n, bn=False, pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights need network access")
    layers, filters = _SPEC[n]
    return VGG(layers, filters, batch_norm=bn, **kw)


def vgg11(**kw):
    return _vgg(11, **kw)


def vgg13(**kw):
    return _vgg(13, **kw)


def vgg16(**kw):
    return _vgg(16, **kw)


def vgg19(**kw):
    return _vgg(19, **kw)


def vgg11_bn(**kw):
    return _vgg(11, bn=True, **kw)


def vgg13_bn(**kw):
    return _vgg(13, bn=True, **kw)


def vgg16_bn(**kw):
    return _vgg(16, bn=True, **kw)


def vgg19_bn(**kw):
    return _vgg(19, bn=True, **kw)

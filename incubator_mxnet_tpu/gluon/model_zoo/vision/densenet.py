"""DenseNet 121/161/169/201 (reference: gluon/model_zoo/vision/densenet.py)."""
from ...block import HybridBlock
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]

_SPEC = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.bn1 = nn.BatchNorm()
            self.conv1 = nn.Conv2D(bn_size * growth_rate, 1, use_bias=False)
            self.bn2 = nn.BatchNorm()
            self.conv2 = nn.Conv2D(growth_rate, 3, padding=1, use_bias=False)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.conv1(F.relu(self.bn1(x)))
        out = self.conv2(F.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return F.concat(x, out, dim=1)


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                stage = nn.HybridSequential(prefix=f"stage{i + 1}_")
                with stage.name_scope():
                    for _ in range(num_layers):
                        stage.add(_DenseLayer(growth_rate, bn_size, dropout))
                self.features.add(stage)
                num_features += num_layers * growth_rate
                if i != len(block_config) - 1:
                    self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                    self.features.add(nn.Conv2D(num_features // 2, 1,
                                                use_bias=False))
                    self.features.add(nn.AvgPool2D(2, 2))
                    num_features //= 2
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _densenet(n, pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights need network access")
    init_f, growth, cfg = _SPEC[n]
    return DenseNet(init_f, growth, cfg, **kw)


def densenet121(**kw):
    return _densenet(121, **kw)


def densenet161(**kw):
    return _densenet(161, **kw)


def densenet169(**kw):
    return _densenet(169, **kw)


def densenet201(**kw):
    return _densenet(201, **kw)

"""Trainer — the optimizer driver.

Reference parity: ``python/mxnet/gluon/trainer.py`` (``Trainer.step``,
``Trainer._init_kvstore``) — SURVEY §2.8, call stack §3.2. Gradient exchange
goes through the kvstore seam; on a device mesh the kvstore is the XLA
collectives layer (SURVEY §2.5 north-star seam), while single-process
multi-replica parameters reduce locally, matching ``kvstore('device')``.
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Union

import jax.numpy as jnp

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params: Union[ParameterDict, Dict[str, Parameter], List[Parameter]],
                 optimizer, optimizer_params: Optional[dict] = None,
                 kvstore: Optional[str] = "device", compression_params=None,
                 update_on_kvstore: Optional[bool] = None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(list(params.keys()))]
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    f"First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contains_sparse = any(p._stype != "default" for p in self._params)
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states: Dict[int, tuple] = {}
        self._states_synced: Dict[int, bool] = {}

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = param_dict

    def _init_kvstore(self):
        """Resolve the gradient-exchange backend lazily, as the reference
        does on the first step (Trainer._init_kvstore)."""
        if self._kvstore_type and not isinstance(self._kvstore_type, str):
            self._kvstore = self._kvstore_type  # explicit KVStore object
        elif self._kvstore_type in (None, "local", "device", "nccl"):
            if self._compression_params:
                # the inline replica reduce has no compression stage; route
                # through a real store rather than silently ignoring the
                # user's convergence-relevant request
                from .. import kvstore as kv
                self._kvstore = kv.create(self._kvstore_type or "device")
            else:
                # Single-process replica reduce handled inline (CommDevice
                # parity); mesh-sharded training uses parallel.* +
                # kvstore('mesh').
                self._kvstore = None
        else:
            from .. import kvstore as kv
            self._kvstore = kv.create(self._kvstore_type)
        if self._kvstore is not None:
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            for i, param in enumerate(self._params):
                if param._data is not None:
                    self._kvstore.init(i, param._check_and_get(param._data, None))
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr: float):
        self._optimizer.set_learning_rate(lr)

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        """Pull the parameter rows named by ``row_id`` into ``out``
        (reference: Trainer._row_sparse_pull behind sparse Embedding).
        full_idx=True means the caller wants every row — a plain copy."""
        if parameter not in self._params:
            raise MXNetError("parameter is not managed by this Trainer")
        # This Trainer applies optimizer updates locally (update-on-kvstore
        # is the mesh/ShardedTrainer path), so the live weight is the
        # parameter itself — the kvstore copy is only the init snapshot.
        from ..kvstore import _select_rows
        w = parameter.data()._data
        if full_idx:
            out._set_data(w.astype(out.dtype))
            return
        out._set_data(_select_rows(w, row_id).astype(out.dtype))

    def allreduce_grads(self):
        """Sum gradients across parameter replicas (kvstore push/pull —
        reference stack §3.4; local CommDevice reduce when single-process).

        With a kvstore attached, ALL eligible keys go through ONE batched
        ``push``/``pull`` pair — the store runs a single compiled
        collective for the whole key batch (grouped ncclAllReduce parity)
        instead of a per-parameter Python loop of host round trips. The
        per-key loop survives only for the async parameter server (whose
        client applies retry/exactly-once semantics per key) and under
        the explicit ``MXTPU_KVSTORE_FALLBACK=1`` opt-in."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None:
            items = [(i, p.list_grad()) for i, p in enumerate(self._params)
                     if p.grad_req != "null" and p._data is not None]
            if not items:
                return
            from ..kvstore import kv_fallback_active
            from ..kvstore.async_ps import AsyncKVStore
            if kv_fallback_active() or isinstance(self._kvstore,
                                                  AsyncKVStore):
                for i, grads in items:
                    self._kvstore.push(i, grads)
                    self._kvstore.pull(i, grads)
            else:
                keys = [i for i, _ in items]
                grads = [g for _, g in items]
                self._kvstore.push(keys, grads)
                self._kvstore.pull(keys, out=grads)
            return
        for param in self._params:
            if param.grad_req == "null" or param._data is None:
                continue
            grads = param.list_grad()
            if len(grads) > 1:
                total = grads[0]._data
                for g in grads[1:]:
                    total = total + g._data.astype(total.dtype)
                for g in grads:
                    g._data = total.astype(g._data.dtype)
                    g._version += 1

    def step(self, batch_size: int, ignore_stale_grad: bool = False):
        """allreduce + optimizer update (reference: Trainer.step)."""
        rescale_grad = self._scale / batch_size
        self._optimizer.rescale_grad = rescale_grad
        if not self._kv_initialized:
            self._init_kvstore()
        self.allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size: int, ignore_stale_grad: bool = False):
        """Optimizer update only — assumes gradients were already reduced
        (the Horovod/custom-allreduce seam, reference: Trainer.update)."""
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad: bool = False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            grads = param.list_grad()
            # Stale-gradient protocol (reference: Trainer._update over
            # Parameter._fresh_grad): a grad is fresh only if backward
            # deposited into it since the last applied update. Stale ⇒
            # UserWarning, or a skipped update under ignore_stale_grad.
            if not all(g._fresh_grad for g in grads):
                if not ignore_stale_grad:
                    raise UserWarning(
                        f"Gradient of Parameter `{param.name}` on context "
                        f"{param.list_ctx()} has not been updated by backward "
                        "since last `step`. This could mean a bug in your "
                        "model that made it only use a subset of the "
                        "Parameters (Blocks) for this iteration. If you are "
                        "intentionally only using a subset, call step with "
                        "ignore_stale_grad=True to suppress this warning")
                continue
            for weight, grad in zip(param.list_data(), grads):
                if i not in self._states:
                    self._states[i] = self._optimizer.create_state_multi_precision(i, weight)
                self._states[i] = self._optimizer.update(
                    i, weight, grad, self._states[i])
                break  # replicas share one update; broadcast below
            datas = param.list_data()
            if len(datas) > 1:
                src = datas[0]
                for w in datas[1:]:
                    w._data = src._data
                    w._version += 1
            for g in grads:
                g._fresh_grad = False

    def save_states(self, fname: str):
        """Serialize optimizer state (reference: Trainer.save_states).
        Atomic: a crash mid-write never clobbers an existing states file."""
        import os
        import numpy as onp
        blob = {
            "num_update": self._optimizer.num_update,
            "states": {i: tuple(onp.asarray(s) for s in st)
                       for i, st in self._states.items()},
        }
        tmp = f"{fname}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(blob, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fname)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load_states(self, fname: str):
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._optimizer.num_update = blob["num_update"]
        self._states = {i: tuple(jnp.asarray(s) for s in st)
                        for i, st in blob["states"].items()}

    # ------------------------------------------------------------------
    # resumable checkpoints (mx.fault.checkpoint): unlike save_states —
    # optimizer state only, reference shape — this covers parameters AND
    # optimizer state AND the update counter in one atomic versioned
    # directory, the same layout ShardedTrainer.save_checkpoint writes.
    # ------------------------------------------------------------------
    def save_checkpoint(self, root: str, keep=3) -> str:
        """One atomic checkpoint directory under ``root`` (params +
        optimizer states + update counter); prunes to the newest ``keep``."""
        import numpy as onp
        from ..fault import checkpoint as ckpt
        arrays = {}
        for i, p in enumerate(self._params):
            if p._data is None:
                raise MXNetError(
                    f"parameter {p.name!r} is uninitialized; initialize "
                    "before save_checkpoint")
            arrays[f"param:{i:04d}"] = p.data().asnumpy()
            for j, s in enumerate(self._states.get(i, ())):
                arrays[f"opt:{i:04d}:{j}"] = onp.asarray(s)
        meta = {
            "trainer": "Trainer", "format": 1,
            "num_update": self._optimizer.num_update,
            "param_names": [p.name for p in self._params],
            "opt_state_sizes": [len(self._states.get(i, ()))
                                for i in range(len(self._params))],
        }
        return ckpt.save_checkpoint(root, arrays, meta,
                                    step=self._optimizer.num_update,
                                    keep=keep)

    def restore_checkpoint(self, root: str, step=None) -> int:
        """Restore parameters + optimizer state from the newest verified
        checkpoint under ``root`` (or an explicit ``step``)."""
        from ..fault import checkpoint as ckpt
        from ..ndarray import NDArray
        if step is None:
            arrays, meta, step = ckpt.load_latest(root)
        else:
            arrays, meta, step = ckpt.load_checkpoint(root, step)
        if meta.get("trainer") != "Trainer" or meta.get("format") != 1:
            raise MXNetError(
                f"checkpoint step {step} was not written by "
                "gluon.Trainer.save_checkpoint")
        if len(meta.get("param_names", [])) != len(self._params):
            raise MXNetError(
                "checkpoint parameter count does not match this Trainer: "
                f"saved {len(meta.get('param_names', []))}, "
                f"live {len(self._params)}")
        sizes = meta["opt_state_sizes"]
        for i, p in enumerate(self._params):
            v = arrays[f"param:{i:04d}"]
            live = p.data()
            if tuple(v.shape) != tuple(live.shape):
                raise MXNetError(
                    f"checkpoint array for parameter {p.name!r} is shape "
                    f"{tuple(v.shape)}, live parameter is {live.shape}")
            p.set_data(NDArray(v))
            if sizes[i]:
                self._states[i] = tuple(
                    jnp.asarray(arrays[f"opt:{i:04d}:{j}"])
                    for j in range(sizes[i]))
            else:
                self._states.pop(i, None)
        self._optimizer.num_update = int(meta["num_update"])
        return step

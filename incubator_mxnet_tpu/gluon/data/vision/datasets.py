"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py).

This environment has no network egress; datasets load from local idx/npz
files when present, and MNIST/FashionMNIST fall back to a deterministic
procedurally-generated stand-in with the same shapes/classes so end-to-end
training and convergence tests run everywhere.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as onp

from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageRecordDataset",
           "ImageFolderDataset"]


def _read_idx_images(path):
    with gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = onp.frombuffer(f.read(), dtype=onp.uint8)
        return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path):
    with gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        return onp.frombuffer(f.read(), dtype=onp.uint8).astype(onp.int32)


def _synthetic_digits(num: int, seed: int, image_size: int = 28):
    """Deterministic MNIST stand-in: each class is a distinct oriented-bar +
    blob glyph with noise — linearly non-trivial, conv-easy (so the LeNet
    convergence gate at ≥97% is meaningful)."""
    rng = onp.random.RandomState(seed)
    labels = rng.randint(0, 10, size=num).astype(onp.int32)
    xs = onp.zeros((num, image_size, image_size, 1), dtype=onp.uint8)
    yy, xx = onp.mgrid[0:image_size, 0:image_size]
    for i in range(num):
        c = labels[i]
        angle = c * onp.pi / 10.0
        # oriented bar through the center
        d = onp.abs((xx - 14) * onp.sin(angle) - (yy - 14) * onp.cos(angle))
        img = (d < 2.0).astype(onp.float32) * 200.0
        # class-dependent blob position
        bx, by = 6 + (c % 5) * 4, 6 + (c // 5) * 12
        blob = onp.exp(-(((xx - bx) ** 2 + (yy - by) ** 2) / 8.0)) * 255.0
        img = onp.clip(img + blob, 0, 255)
        jx, jy = rng.randint(-2, 3), rng.randint(-2, 3)
        img = onp.roll(onp.roll(img, jx, axis=1), jy, axis=0)
        img = img + rng.randn(image_size, image_size) * 12.0
        xs[i, :, :, 0] = onp.clip(img, 0, 255).astype(onp.uint8)
    return xs, labels


class MNIST(ArrayDataset):
    """MNIST (reference: gluon.data.vision.MNIST). Loads the standard idx
    files from ``root`` when present; synthesizes a stand-in otherwise."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }
    _synthetic_sizes = {True: 20000, False: 4000}

    def __init__(self, root: str = os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train: bool = True, transform=None):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        data, label = self._get_data()
        super().__init__(data, label)

    def _get_data(self):
        imgf, lblf = self._files[self._train]
        for ext in ("", ".gz"):
            ip = os.path.join(self._root, imgf + ext)
            lp = os.path.join(self._root, lblf + ext)
            if os.path.exists(ip) and os.path.exists(lp):
                return _read_idx_images(ip), _read_idx_labels(lp)
        return _synthetic_digits(self._synthetic_sizes[self._train],
                                 seed=42 if self._train else 43)

    def __getitem__(self, idx):
        data, label = super().__getitem__(idx)
        data = data.astype(onp.float32)
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class FashionMNIST(MNIST):
    _synthetic_sizes = {True: 20000, False: 4000}

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root: str = os.path.join("~", ".mxnet", "datasets",
                                                "fashion-mnist"),
                 train: bool = True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(ArrayDataset):
    """CIFAR10 (reference: gluon.data.vision.CIFAR10); local bin files or a
    32×32×3 procedural stand-in."""

    _num_classes = 10

    def __init__(self, root: str = os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train: bool = True, transform=None):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        data, label = self._get_data()
        super().__init__(data, label)

    def _load_bins(self, files):
        xs, ys = [], []
        for fn in files:
            raw = onp.fromfile(fn, dtype=onp.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0].astype(onp.int32))
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        return onp.concatenate(xs), onp.concatenate(ys)

    def _get_data(self):
        base = os.path.join(self._root, "cifar-10-batches-bin")
        if self._train:
            files = [os.path.join(base, f"data_batch_{i}.bin") for i in range(1, 6)]
        else:
            files = [os.path.join(base, "test_batch.bin")]
        if all(os.path.exists(f) for f in files):
            return self._load_bins(files)
        n = 10000 if self._train else 2000
        rng = onp.random.RandomState(7 if self._train else 8)
        labels = rng.randint(0, self._num_classes, size=n).astype(onp.int32)
        xs = onp.zeros((n, 32, 32, 3), dtype=onp.uint8)
        for i in range(n):
            c = labels[i]
            img = rng.randn(32, 32, 3) * 20 + 60
            img[:, :, c % 3] += 80 + 10 * (c // 3)
            img[(c * 3) % 28:(c * 3) % 28 + 4, :, :] += 60
            xs[i] = onp.clip(img, 0, 255).astype(onp.uint8)
        return xs, labels

    def __getitem__(self, idx):
        data, label = super().__getitem__(idx)
        data = data.astype(onp.float32)
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class CIFAR100(CIFAR10):
    _num_classes = 100

    def __init__(self, root: str = os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label: bool = False, train: bool = True, transform=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform)


class ImageRecordDataset(Dataset):
    """Dataset over an ImageRecordIO pack (reference:
    gluon.data.vision.ImageRecordDataset over im2rec packs)."""

    def __init__(self, filename: str, flag: int = 1, transform=None):
        from .... import recordio, image
        self._rio = recordio
        self._image = image
        self._record = recordio.IndexedRecordIO(
            filename[: filename.rfind(".")] + ".idx", filename, "r")
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        record = self._record.read_idx(self._record.keys[idx])
        header, img = self._rio.unpack(record)
        arr = self._image.imdecode(img, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(arr, label)
        return arr, label


class ImageFolderDataset(Dataset):
    """Folder-of-class-folders dataset (reference: ImageFolderDataset)."""

    def __init__(self, root: str, flag: int = 1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if filename.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, filename), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image
        fn, label = self.items[idx]
        if fn.endswith(".npy"):
            img = onp.load(fn)
        else:
            with open(fn, "rb") as f:
                img = image.imdecode(f.read(), flag=self._flag).asnumpy()
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

"""Vision transforms (reference: python/mxnet/gluon/data/vision/transforms.py).

Transforms run on the host (numpy) inside DataLoader workers — the TPU-era
placement of the reference's C++ augmenter threads (SURVEY §2.6).
"""
from __future__ import annotations

from typing import Sequence

import numpy as onp

from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import HybridSequential, Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomLighting"]


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class Compose(Sequential):
    """Chain transforms (reference: transforms.Compose)."""

    def __init__(self, transforms: Sequence):
        super().__init__(prefix="")
        for t in transforms:
            self.add(t if isinstance(t, Block) else _FuncTransform(t))


class _FuncTransform(Block):
    def __init__(self, fn):
        super().__init__(prefix="")
        self._fn = fn

    def forward(self, x):
        return self._fn(x)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__(prefix="")
        self._dtype = dtype

    def forward(self, x):
        return _to_numpy(x).astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference: ToTensor)."""

    def forward(self, x):
        x = _to_numpy(x).astype(onp.float32) / 255.0
        if x.ndim == 3:
            return onp.transpose(x, (2, 0, 1))
        return onp.transpose(x, (0, 3, 1, 2))


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__(prefix="")
        self._mean = onp.asarray(mean, dtype=onp.float32)
        self._std = onp.asarray(std, dtype=onp.float32)

    def forward(self, x):
        x = _to_numpy(x).astype(onp.float32)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return (x - mean) / std


def _resize_hwc(img, size):
    """Nearest+bilinear host resize without external deps."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        ow, oh = size, size
    else:
        ow, oh = size
    ys = onp.linspace(0, h - 1, oh)
    xs = onp.linspace(0, w - 1, ow)
    y0 = onp.floor(ys).astype(int)
    x0 = onp.floor(xs).astype(int)
    y1 = onp.minimum(y0 + 1, h - 1)
    x1 = onp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype(onp.float32)
    out = (img[y0][:, x0] * (1 - wy) * (1 - wx) + img[y0][:, x1] * (1 - wy) * wx
           + img[y1][:, x0] * wy * (1 - wx) + img[y1][:, x1] * wy * wx)
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__(prefix="")
        self._size = size

    def forward(self, x):
        return _resize_hwc(_to_numpy(x), self._size)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__(prefix="")
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        x = _to_numpy(x)
        h, w = x.shape[:2]
        cw, ch = self._size
        x0 = max(0, (w - cw) // 2)
        y0 = max(0, (h - ch) // 2)
        return x[y0:y0 + ch, x0:x0 + cw]


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__(prefix="")
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        x = _to_numpy(x)
        if self._pad:
            p = self._pad
            x = onp.pad(x, ((p, p), (p, p), (0, 0)), mode="constant")
        h, w = x.shape[:2]
        cw, ch = self._size
        x0 = onp.random.randint(0, max(1, w - cw + 1))
        y0 = onp.random.randint(0, max(1, h - ch + 1))
        return x[y0:y0 + ch, x0:x0 + cw]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__(prefix="")
        self._size = size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        x = _to_numpy(x)
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = onp.random.uniform(*self._scale) * area
            log_ratio = (onp.log(self._ratio[0]), onp.log(self._ratio[1]))
            aspect = onp.exp(onp.random.uniform(*log_ratio))
            cw = int(round((target_area * aspect) ** 0.5))
            ch = int(round((target_area / aspect) ** 0.5))
            if cw <= w and ch <= h:
                x0 = onp.random.randint(0, w - cw + 1)
                y0 = onp.random.randint(0, h - ch + 1)
                crop = x[y0:y0 + ch, x0:x0 + cw]
                return _resize_hwc(crop, self._size)
        return _resize_hwc(x, self._size)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        x = _to_numpy(x)
        if onp.random.rand() < 0.5:
            return x[:, ::-1].copy()
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        x = _to_numpy(x)
        if onp.random.rand() < 0.5:
            return x[::-1].copy()
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__(prefix="")
        self._b = brightness

    def forward(self, x):
        x = _to_numpy(x).astype(onp.float32)
        alpha = 1.0 + onp.random.uniform(-self._b, self._b)
        return x * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__(prefix="")
        self._c = contrast

    def forward(self, x):
        x = _to_numpy(x).astype(onp.float32)
        alpha = 1.0 + onp.random.uniform(-self._c, self._c)
        gray = x.mean()
        return x * alpha + gray * (1 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__(prefix="")
        self._s = saturation

    def forward(self, x):
        x = _to_numpy(x).astype(onp.float32)
        alpha = 1.0 + onp.random.uniform(-self._s, self._s)
        gray = x.mean(axis=-1, keepdims=True)
        return x * alpha + gray * (1 - alpha)


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise."""

    _eigval = onp.asarray([55.46, 4.794, 1.148])
    _eigvec = onp.asarray([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])

    def __init__(self, alpha):
        super().__init__(prefix="")
        self._alpha = alpha

    def forward(self, x):
        x = _to_numpy(x).astype(onp.float32)
        alpha = onp.random.normal(0, self._alpha, size=(3,))
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return x + rgb

"""Data pipeline: Dataset / Sampler / DataLoader (reference:
python/mxnet/gluon/data/ — SURVEY §2.6)."""
from .dataset import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .dataloader import *  # noqa: F401,F403
from . import vision  # noqa: F401

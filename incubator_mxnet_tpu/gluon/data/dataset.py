"""Dataset abstractions (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as onp

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract random-access dataset."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn: Callable) -> "Dataset":
        return SimpleDataset([s for s in self if fn(s)])

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Every num_shards-th sample, offset by index (reference:
        Dataset.shard — multi-worker data split)."""
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return SimpleDataset([self[i] for i in range(start, end)])

    def take(self, count) -> "Dataset":
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def transform(self, fn: Callable, lazy: bool = True) -> "Dataset":
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn: Callable, lazy: bool = True) -> "Dataset":
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data: Sequence):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/datasets (reference: ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                f"All arrays must have the same length; {len(data)} != {self._length}"
            from ...ndarray import NDArray
            if isinstance(data, NDArray):
                data = data.asnumpy()
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference: gluon.data.RecordFileDataset
    over dmlc recordio — SURVEY §2.6)."""

    def __init__(self, filename: str):
        from ... import recordio
        self._record = recordio.IndexedRecordIO(
            filename[: filename.rfind(".")] + ".idx", filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

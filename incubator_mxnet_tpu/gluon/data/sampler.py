"""Samplers (reference: python/mxnet/gluon/data/sampler.py)."""
from __future__ import annotations

import numpy as onp

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "FixedBucketSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start: int = 0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        indices = onp.random.permutation(self._length)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Groups a sampler's indices into batches (reference: BatchSampler;
    last_batch in {'keep','discard','rollover'})."""

    def __init__(self, sampler: Sampler, batch_size: int, last_batch: str = "keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    f"last_batch must be one of 'keep', 'discard', or "
                    f"'rollover', but got {self._last_batch}")

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) // self._batch_size
        raise ValueError(
            f"last_batch must be one of 'keep', 'discard', or 'rollover', "
            f"but got {self._last_batch}")


class FixedBucketSampler(Sampler):
    """Length-bucketing batch sampler (GluonNLP FixedBucketSampler — the
    reference's answer to dynamic sequence lengths, SURVEY §5.7; on TPU this
    is also the *padding* strategy that keeps XLA shapes static)."""

    def __init__(self, lengths, batch_size, num_buckets=10, ratio=0.0,
                 shuffle=False, bucket_keys=None):
        self._lengths = onp.asarray(lengths)
        self._batch_size = batch_size
        self._shuffle = shuffle
        mn, mx = int(self._lengths.min()), int(self._lengths.max())
        if bucket_keys is None:
            if num_buckets <= 1:
                bucket_keys = [mx]
            else:
                step = max(1, (mx - mn) // num_buckets)
                bucket_keys = list(range(mn + step, mx, step)) + [mx]
        self._bucket_keys = sorted(set(int(k) for k in bucket_keys))
        buckets = {k: [] for k in self._bucket_keys}
        for i, l in enumerate(self._lengths):
            for k in self._bucket_keys:
                if l <= k:
                    buckets[k].append(i)
                    break
        self._batches = []
        for k, idxs in buckets.items():
            # larger batches for shorter buckets when ratio > 0
            bs = max(int(batch_size * (1 + ratio * (self._bucket_keys[-1] - k)
                                       / self._bucket_keys[-1])), batch_size) \
                if ratio > 0 else batch_size
            for s in range(0, len(idxs), bs):
                self._batches.append(idxs[s:s + bs])

    @property
    def bucket_keys(self):
        return self._bucket_keys

    def __iter__(self):
        order = onp.random.permutation(len(self._batches)) if self._shuffle \
            else range(len(self._batches))
        for i in order:
            yield self._batches[i]

    def __len__(self):
        return len(self._batches)

    def stats(self) -> str:
        return (f"FixedBucketSampler: {len(self._batches)} batches, "
                f"keys={self._bucket_keys}")

"""DataLoader with background workers.

Reference parity: ``python/mxnet/gluon/data/dataloader.py`` — multiprocessing
workers producing batches into shared-memory NDArrays (SURVEY §3.6). Worker
batches travel through the SAME transport as the reference: named POSIX
shared memory (the native ``ShmSegment``) — a worker writes each batch array
into a segment and only (name, shape, dtype) crosses the pipe; the parent
attaches zero-copy and hands the buffer to XLA's async H2D (the reference's
dedicated copy thread). When the native library is unavailable the loader
falls back to pickle-over-pipe transparently (MXTPU_DATALOADER_SHM=0 forces
the fallback).
"""
from __future__ import annotations

import multiprocessing
import os
import queue as queue_mod
import threading
import uuid
from typing import Callable, List, Optional

import numpy as onp

from ...context import cpu, current_context
from ...ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


# ---------------------------------------------------------------------------
# shared-memory batch transport (reference: the C++ shm NDArray transport)
# ---------------------------------------------------------------------------

def _shm_available() -> bool:
    if os.environ.get("MXTPU_DATALOADER_SHM", "1") == "0":
        return False
    try:
        from ...native import _lib
        _lib()
        return True
    except Exception:
        return False


_SHM_TAG = "__mxtpu_shm__"


def _to_shm(tree):
    """Worker side: move every ndarray into a named shm segment; the pipe
    carries only descriptors."""
    from ...native import ShmSegment
    if isinstance(tree, (list, tuple)):
        return [_to_shm(t) for t in tree]
    if isinstance(tree, onp.ndarray) and tree.nbytes > 0:
        arr = onp.ascontiguousarray(tree)
        name = f"/mxtpu_dl_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        seg = ShmSegment(name, arr.nbytes, create=True)
        seg.as_numpy(arr.shape, arr.dtype)[...] = arr
        # keep the segment alive for the parent; parent unlinks
        seg.close(unlink=False)
        return (_SHM_TAG, name, arr.shape, str(arr.dtype))
    return tree


def _is_shm_desc(tree) -> bool:
    return (isinstance(tree, (list, tuple)) and len(tree) == 4
            and isinstance(tree[0], str) and tree[0] == _SHM_TAG)


def _from_shm(tree):
    """Parent side: attach, copy out, unlink."""
    from ...native import ShmSegment
    if _is_shm_desc(tree):
        _, name, shape, dtype = tree
        n = max(1, int(onp.prod(shape))) * onp.dtype(dtype).itemsize
        seg = ShmSegment(name, n, create=False)
        try:
            arr = onp.array(seg.as_numpy(shape, onp.dtype(dtype)))
        finally:
            seg.close(unlink=True)
        return arr
    if isinstance(tree, (list, tuple)):
        return [_from_shm(t) for t in tree]
    return tree


def _unlink_shm(tree) -> None:
    """Free a descriptor tree's segments without reading them (cleanup for
    batches the consumer abandoned — named shm outlives the process)."""
    from ...native import ShmSegment
    if _is_shm_desc(tree):
        _, name, shape, dtype = tree
        n = max(1, int(onp.prod(shape))) * onp.dtype(dtype).itemsize
        try:
            ShmSegment(name, n, create=False).close(unlink=True)
        except Exception:
            pass
        return
    if isinstance(tree, (list, tuple)):
        for t in tree:
            _unlink_shm(t)


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return NDArray(onp.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    arr = onp.asarray(data)
    return NDArray(arr)


def _numpy_batchify(data):
    """Worker-side batchify: keep numpy (no device handles cross processes)."""
    if isinstance(data[0], tuple):
        return [_numpy_batchify(d) for d in zip(*data)]
    if isinstance(data[0], NDArray):
        return onp.stack([d.asnumpy() for d in data])
    return onp.asarray(data)


default_mp_batchify_fn = _numpy_batchify


def _as_nd(batch):
    if isinstance(batch, (list, tuple)):
        return [_as_nd(b) for b in batch]
    if isinstance(batch, onp.ndarray):
        return NDArray(batch)
    return batch


_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(samples, batchify_fn, use_shm=False):
    batch = batchify_fn([_worker_dataset[i] for i in samples])
    return _to_shm(batch) if use_shm else batch


class DataLoader:
    """Iterate a Dataset in (optionally shuffled) mini-batches.

    num_workers > 0 uses a multiprocessing pool (reference's worker
    processes); prefetch overlaps batch assembly with training either way.
    """

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[Sampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 prefetch: Optional[int] = None, thread_pool: bool = False,
                 timeout: int = 120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers or 2)
        self._thread_pool = thread_pool
        if batchify_fn is None:
            self._batchify_fn = _numpy_batchify
        else:
            self._batchify_fn = batchify_fn
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(self._num_workers)
            else:
                ctx = multiprocessing.get_context("fork")
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(self._dataset,))

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._pool is not None:
            return self._multi_worker_iter()
        return self._prefetch_iter()

    def _load(self, samples):
        return self._batchify_fn([self._dataset[i] for i in samples])

    def _prefetch_iter(self):
        """Single-process iteration with a background prefetch thread
        (reference: PrefetchingIter / ThreadedIter in dmlc-core)."""
        q: "queue_mod.Queue" = queue_mod.Queue(self._prefetch)
        sentinel = object()

        def producer():
            try:
                for samples in self._batch_sampler:
                    q.put(self._load(samples))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer,
                             name="mx-dataloader-prefetch", daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield _as_nd(item)

    def _multi_worker_iter(self):
        if self._thread_pool:
            results = [
                self._pool.apply_async(self._load, (samples,))
                for samples in self._batch_sampler]
            for r in results:
                yield _as_nd(r.get(self._timeout))
            return
        use_shm = _shm_available()
        results = [
            self._pool.apply_async(_worker_fn,
                                   (samples, self._batchify_fn, use_shm))
            for samples in self._batch_sampler]
        done = 0
        try:
            for r in results:
                batch = r.get(self._timeout)
                done += 1
                if use_shm:
                    batch = _from_shm(batch)
                yield _as_nd(batch)
        finally:
            if use_shm and done < len(results):
                # consumer abandoned the iterator (break / exception):
                # drain and unlink the already-dispatched segments so
                # /dev/shm doesn't fill up across runs
                for r in results[done:]:
                    try:
                        _unlink_shm(r.get(self._timeout))
                    except Exception:
                        pass

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()

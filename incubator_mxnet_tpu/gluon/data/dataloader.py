"""DataLoader with background workers.

Reference parity: ``python/mxnet/gluon/data/dataloader.py`` — multiprocessing
workers producing batches into shared-memory NDArrays (SURVEY §3.6). The
TPU-era shape: workers produce *host numpy* batches (the C++ shm transport's
job collapses into pickle-over-pipe of numpy buffers); the main process
converts once to device arrays, and XLA's async dispatch overlaps H2D with
compute (the reference's dedicated copy thread).
"""
from __future__ import annotations

import multiprocessing
import queue as queue_mod
import threading
from typing import Callable, List, Optional

import numpy as onp

from ...context import cpu, current_context
from ...ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return NDArray(onp.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    arr = onp.asarray(data)
    return NDArray(arr)


def _numpy_batchify(data):
    """Worker-side batchify: keep numpy (no device handles cross processes)."""
    if isinstance(data[0], tuple):
        return [_numpy_batchify(d) for d in zip(*data)]
    if isinstance(data[0], NDArray):
        return onp.stack([d.asnumpy() for d in data])
    return onp.asarray(data)


default_mp_batchify_fn = _numpy_batchify


def _as_nd(batch):
    if isinstance(batch, (list, tuple)):
        return [_as_nd(b) for b in batch]
    if isinstance(batch, onp.ndarray):
        return NDArray(batch)
    return batch


_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(samples, batchify_fn):
    return batchify_fn([_worker_dataset[i] for i in samples])


class DataLoader:
    """Iterate a Dataset in (optionally shuffled) mini-batches.

    num_workers > 0 uses a multiprocessing pool (reference's worker
    processes); prefetch overlaps batch assembly with training either way.
    """

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[Sampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 prefetch: Optional[int] = None, thread_pool: bool = False,
                 timeout: int = 120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers or 2)
        self._thread_pool = thread_pool
        if batchify_fn is None:
            self._batchify_fn = _numpy_batchify
        else:
            self._batchify_fn = batchify_fn
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(self._num_workers)
            else:
                ctx = multiprocessing.get_context("fork")
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(self._dataset,))

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._pool is not None:
            return self._multi_worker_iter()
        return self._prefetch_iter()

    def _load(self, samples):
        return self._batchify_fn([self._dataset[i] for i in samples])

    def _prefetch_iter(self):
        """Single-process iteration with a background prefetch thread
        (reference: PrefetchingIter / ThreadedIter in dmlc-core)."""
        q: "queue_mod.Queue" = queue_mod.Queue(self._prefetch)
        sentinel = object()

        def producer():
            try:
                for samples in self._batch_sampler:
                    q.put(self._load(samples))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield _as_nd(item)

    def _multi_worker_iter(self):
        if self._thread_pool:
            results = [
                self._pool.apply_async(self._load, (samples,))
                for samples in self._batch_sampler]
        else:
            results = [
                self._pool.apply_async(_worker_fn, (samples, self._batchify_fn))
                for samples in self._batch_sampler]
        for r in results:
            yield _as_nd(r.get(self._timeout))

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()

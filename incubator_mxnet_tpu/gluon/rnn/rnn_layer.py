"""Fused RNN layers (reference: python/mxnet/gluon/rnn/rnn_layer.py).

The reference dispatches to cuDNN's fused RNN on GPU (src/operator/rnn.cc,
cudnn_rnn-inl.h); here the fused op is a ``lax.scan`` compiled by XLA — the
whole multi-layer (bi)RNN is one executable. Parameters are registered
per-layer/direction exactly like the reference (``l0_i2h_weight`` …) and
flattened into the fused vector at call time, so checkpoints interchange.
"""
from __future__ import annotations

from typing import List, Optional

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        self._mode = mode
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    self._register_param(
                        f"{j}{i}_i2h_weight", (ng * nh, ni),
                        i2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_weight", (ng * nh, nh),
                        h2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_i2h_bias", (ng * nh,), i2h_bias_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_bias", (ng * nh,), h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **kwargs))
        return states

    def infer_shape(self, inputs, *args):
        ni = int(inputs.shape[2 if self._layout == "NTC" else 2])
        ni = int(inputs.shape[-1])
        nh, ng = self._hidden_size, self._gates
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nh * self._dir

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=getattr(inputs, "context", None),
                                      dtype=str(inputs.dtype))
        if not isinstance(states, (list, tuple)):
            states = [states]
        # flatten params in the fused cuDNN order: all weights, then biases
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                ws.append(F.reshape(params[f"{j}{i}_i2h_weight"], (-1,)))
                ws.append(F.reshape(params[f"{j}{i}_h2h_weight"], (-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                bs.append(params[f"{j}{i}_i2h_bias"])
                bs.append(params[f"{j}{i}_h2h_bias"])
        flat = F.concat(*(ws + bs), dim=0)
        outs = F.RNN(inputs, flat, *states, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        if self._mode == "lstm":
            outputs, states = outs[0], [outs[1], outs[2]]
        else:
            outputs, states = outs[0], [outs[1]]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return outputs, states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (tanh or relu) — reference: gluon.rnn.RNN."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM — reference: gluon.rnn.LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU — reference: gluon.rnn.GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]

"""Recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py).

Cells are per-step HybridBlocks; ``unroll`` composes them over time. On the
hybridized path the whole unroll compiles into one XLA program (the scan is
unrolled at trace time for static lengths, matching the reference's
explicit-unroll semantics).
"""
from __future__ import annotations

from typing import List, Optional

from ...base import MXNetError, _as_list
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "BidirectionalCell",
           "ResidualCell", "ModifierCell", "ZoneoutCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    from ... import ndarray as F
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        in_axis = in_layout.find("T") if in_layout else axis
        if merge is True:
            inputs = [F.expand_dims(i, axis=axis) for i in inputs]
            inputs = F.concat(*inputs, dim=axis)
        return inputs, axis, batch_axis
    if axis != 0 and not isinstance(inputs, (list, tuple)):
        pass
    if merge is False:
        seq = F.split(inputs, num_outputs=length or inputs.shape[axis],
                      axis=axis, squeeze_axis=True)
        if not isinstance(seq, (list, tuple)):
            seq = [seq]
        return list(seq), axis, batch_axis
    return inputs, axis, batch_axis


class RecurrentCell(HybridBlock):
    """Base recurrent cell."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over ``length`` steps (reference:
        RecurrentCell.unroll)."""
        from ... import ndarray as F
        self.reset()
        if not isinstance(inputs, (list, tuple)):
            batch_size = inputs.shape[layout.find("N")]
        else:
            batch_size = inputs[0].shape[0]
        inputs, axis, batch_axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(
                batch_size=batch_size, ctx=inputs[0].context,
                dtype=str(inputs[0].dtype))
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [F.SequenceLast(F.stack(*ele_list, axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis)
        if merge_outputs:
            outputs = [F.expand_dims(o, axis=axis) for o in outputs]
            outputs = F.concat(*outputs, dim=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis):
    assert isinstance(data, list)
    ele_length = [F.SequenceMask(F.expand_dims(d, axis=0),
                                 sequence_length=valid_length,
                                 use_sequence_length=True, axis=0)
                  for d in data]
    return [F.squeeze(d, axis=0) for d in ele_length]


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell (reference: gluon.rnn.RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (reference: gluon.rnn.LSTMCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer,
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer,
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=-1)
        in_gate = F.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = F.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = F.Activation(slice_gates[2], act_type="tanh")
        out_gate = F.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (reference: gluon.rnn.GRUCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,), init=i2h_bias_initializer,
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,), init=h2h_bias_initializer,
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=-1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in order each step."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """Dropout applied each step (reference: gluon.rnn.DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            from ... import autograd, random as random_mod
            if autograd.is_training():
                key = random_mod.next_key(getattr(inputs, "context", None))
                inputs = F.Dropout(inputs, p=self._rate, axes=self._axes,
                                   training=True, key=key)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that wrap another cell (reference: ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: gluon.rnn.ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        from ... import autograd, random as random_mod
        next_output, next_states = self.base_cell(inputs, states)
        if autograd.is_training():
            ctx = getattr(inputs, "context", None)
            p_out, p_st = self._zoneout_outputs, self._zoneout_states

            def mask(p, like):
                key = random_mod.next_key(ctx)
                return F.Dropout(F.ones_like(like), p=p, training=True, key=key)

            prev_output = self._prev_output if self._prev_output is not None \
                else F.zeros_like(next_output)
            output = (F.where(mask(p_out, next_output), next_output, prev_output)
                      if p_out != 0.0 else next_output)
            new_states = ([F.where(mask(p_st, ns), ns, s) for ns, s in
                           zip(next_states, states)] if p_st != 0.0 else next_states)
            # reference-parity zoneout state: reset()/begin_state clears it
            # before any cross-trace reuse, so the stored value never
            # outlives its trace (the generic leak MX206 guards against)
            self._prev_output = output  # mxlint: disable=MX206
            return output, new_states
        return next_output, next_states


class ResidualCell(ModifierCell):
    """Adds the input to the output each step."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def _alias(self):
        return "residual"


class BidirectionalCell(HybridRecurrentCell):
    """Runs one cell forward and one backward over the sequence; unroll-only."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        if not isinstance(inputs, (list, tuple)):
            batch_size = inputs.shape[layout.find("N")]
        else:
            batch_size = inputs[0].shape[0]
        inputs, axis, batch_axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(
                batch_size=batch_size, ctx=inputs[0].context,
                dtype=str(inputs[0].dtype))
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)), begin_state=states[n_l:],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_outputs = list(reversed(r_outputs))
        outputs = [F.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = [F.expand_dims(o, axis=axis) for o in outputs]
            outputs = F.concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states


#: hybridizable sequential cell — same semantics here (every cell is
#: trace-compatible), kept as a distinct name for reference parity
HybridSequentialRNNCell = SequentialRNNCell

"""Gluon — the imperative + hybrid high-level API (reference:
python/mxnet/gluon/ — SURVEY §2.8)."""
from . import _trace  # noqa: F401
from .parameter import (  # noqa: F401
    Constant, DeferredInitializationError, Parameter, ParameterDict,
)
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import utils  # noqa: F401

import importlib as _importlib

_LAZY = {"rnn": ".rnn", "data": ".data", "model_zoo": ".model_zoo",
         "contrib": ".contrib"}


def __getattr__(name):
    if name in _LAZY:
        mod = _importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

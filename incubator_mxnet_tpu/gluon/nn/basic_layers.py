"""Basic neural-network layers.

Reference parity: ``python/mxnet/gluon/nn/basic_layers.py`` (Dense, Dropout,
BatchNorm, LayerNorm, Embedding, Flatten, Sequential…) — SURVEY §2.8.
"""
from __future__ import annotations

from typing import Optional

import numpy as onp

from ...base import MXNetError
from ... import autograd
from ... import random as random_mod
from ...ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Identity"]


class Sequential(Block):
    """Stack of Blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; compilable as one cached op."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: out = act(dot(x, W^T) + b).

    Reference: gluon.nn.Dense over the FullyConnected op
    (src/operator/nn/fully_connected.cc). Weight layout (units, in_units),
    matching the reference, lowered to a single MXU matmul by XLA.
    """

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer, dtype=dtype,
                    allow_deferred_init=True)
            else:
                self.bias = None
            from .activations import Activation
            self.act = Activation(activation, prefix=activation + "_") if activation else None

    def infer_shape(self, x, *args):
        in_units = int(onp.prod(x.shape[1:])) if self._flatten else int(x.shape[-1])
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return f"Dense({shape[1] if shape and len(shape) > 1 else None} -> {self._units})"


class Dropout(HybridBlock):
    """Dropout (reference: src/operator/nn/dropout.cc). Randomness is drawn
    from the stateful per-Context stream eagerly, or threaded through the
    cached-op key input when hybridized."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        training = autograd.is_training()
        if not training or self._rate <= 0:
            return x
        key = random_mod.next_key(getattr(x, "context", None))
        return F.Dropout(x, p=self._rate, axes=self._axes, training=True, key=key)


class BatchNorm(HybridBlock):
    """Batch normalization with running-stat aux state.

    Reference: src/operator/nn/batch_norm.cc — the op mutates its aux states
    in place; here the op returns batch stats and the layer deposits the EMA
    update into the aux Parameters (trace-aware, see Parameter._deposit_aux).
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # norm stats stay fp32 (AMP discipline)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = autograd.is_training()
        out, m, v = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis,
            training=training)
        if training and not self._use_global_stats:
            mom = self._momentum
            ctx = getattr(x, "context", None)
            with autograd.pause():
                new_mean = running_mean * mom + m * (1 - mom)
                new_var = running_var * mom + v * (1 - mom)
            self.running_mean._deposit_aux(new_mean, ctx)
            self.running_var._deposit_aux(new_var, ctx)
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: gluon.contrib.SyncBatchNorm).

    Under pjit/shard_map the batch axis is sharded and XLA computes global
    batch statistics via the mesh collective inserted by psum — so on the
    SPMD path this is exactly BatchNorm; the num_devices arg is accepted for
    API parity.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class LayerNorm(HybridBlock):
    """Layer normalization (reference: src/operator/nn/layer_norm.cc)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Group normalization (reference: src/operator/nn/group_norm.cc)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer, allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    """Instance normalization (reference: src/operator/instance_norm.cc)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer, allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Embedding lookup (reference: src/operator/tensor/indexing_op.cc
    Embedding). take/gather lowers to a one-hot matmul or dynamic-gather on
    TPU as XLA sees fit."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        if sparse_grad:
            # row_sparse gradients are a CPU-era optimization; dense grads on
            # TPU (documented divergence, SURVEY §7 sparse scoping).
            sparse_grad = False
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Flatten all dims but the batch axis."""

    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    """Pass-through block."""

    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    """Wrap an arbitrary function as a Block (reference: gluon.nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    """Wrap a pure F-style function as a HybridBlock."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else function.__name__
        self._func = function

    def hybrid_forward(self, F, x, *args):
        if isinstance(self._func, str):
            return getattr(F, self._func)(x, *args)
        return self._func(F, x, *args)

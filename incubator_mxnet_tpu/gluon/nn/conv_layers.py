"""Convolution and pooling layers.

Reference parity: ``python/mxnet/gluon/nn/conv_layers.py`` over
``src/operator/nn/convolution.cc`` / ``pooling.cc``. Layout is NCHW/NCW/NCDHW
(the reference default); weights are (out_channels, in_channels/groups,
*kernel). XLA retiles onto the MXU regardless of logical layout.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as onp

from ..block import HybridBlock
from .activations import Activation

__all__ = [
    "Conv1D", "Conv2D", "Conv3D",
    "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
    "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
    "ReflectionPad2D",
]


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size,
            "stride": _tup(strides, ndim),
            "dilate": _tup(dilation, ndim),
            "pad": _tup(padding, ndim),
            "num_filter": channels,
            "num_group": groups,
            "layout": layout,
        }
        if adj is not None:
            self._kwargs["adj"] = _tup(adj, ndim)
        self._op_name = op_name
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) + tuple(kernel_size)
            else:  # Deconvolution: (in_channels, channels//groups, *k)
                wshape = (in_channels if in_channels else 0, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") if activation else None

    def infer_shape(self, x, *args):
        in_c = int(x.shape[1])
        k = tuple(self._kwargs["kernel"])
        g = self._kwargs["num_group"]
        if self._op_name == "Convolution":
            self.weight.shape = (self._channels, in_c // g) + k
        else:
            self.weight.shape = (in_c, self._channels // g) + k

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, no_bias=bias is None, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kwargs['kernel']}, stride={self._kwargs['stride']})")


class Conv1D(_Conv):
    """1-D convolution over NCW data (reference: gluon.nn.Conv1D)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    """2-D convolution over NCHW data (reference: gluon.nn.Conv2D)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    """3-D convolution over NCDHW data (reference: gluon.nn.Conv3D)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    """Transposed 1-D convolution (reference: gluon.nn.Conv1DTranspose)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    """Transposed 2-D convolution (reference: gluon.nn.Conv2DTranspose)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    """Transposed 3-D convolution (reference: gluon.nn.Conv3DTranspose)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        ndim = len(pool_size)
        self._kwargs = {
            "kernel": pool_size,
            "stride": _tup(strides, ndim),
            "pad": _tup(padding, ndim),
            "global_pool": global_pool,
            "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
        }
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


class MaxPool1D(_Pooling):
    """Max pooling over NCW data."""

    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    """Max pooling over NCHW data."""

    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    """Max pooling over NCDHW data."""

    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    """Average pooling over NCW data."""

    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    """Average pooling over NCHW data."""

    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    """Average pooling over NCDHW data."""

    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    """Global max pooling to a single value per channel (NCW)."""

    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    """Global max pooling to a single value per channel (NCHW)."""

    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    """Global max pooling to a single value per channel (NCDHW)."""

    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "max", layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    """Global average pooling to a single value per channel (NCW)."""

    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    """Global average pooling to a single value per channel (NCHW)."""

    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    """Global average pooling to a single value per channel (NCDHW)."""

    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "avg", layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reflection padding over the spatial dims of NCHW data."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)

"""Activation layers (reference: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU", "SiLU"]


class Activation(HybridBlock):
    """Element-wise activation by name (relu/sigmoid/tanh/softrelu/...)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    """Leaky ReLU: x if x>0 else alpha*x."""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    """Parametric ReLU with a learnable per-channel negative slope."""

    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,),
                init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    """Exponential linear unit."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled exponential linear unit (self-normalizing nets)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    """Swish/SiLU activation: x * sigmoid(beta * x)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.Activation(x * self._beta, act_type="sigmoid")


class GELU(HybridBlock):
    """TPU-era addition (BERT path): tanh-approximate GELU."""

    def hybrid_forward(self, F, x):
        return F.gelu_tanh(x)


class SiLU(HybridBlock):
    """Sigmoid-weighted linear unit, x * sigmoid(x)."""

    def hybrid_forward(self, F, x):
        return F.silu(x)

"""Parameter and ParameterDict.

Reference parity: ``python/mxnet/gluon/parameter.py`` (``Parameter._init_impl``,
deferred init, per-device replicas via ``list_data``, ``grad_req``) — SURVEY
§2.8. TPU-era differences: replicas are keyed by :class:`Context` over PjRt
buffers, and while a HybridBlock cache is being traced, ``data()`` returns the
trace proxy so parameters become jit inputs rather than baked constants.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import initializer as init_mod
from ..ndarray import NDArray
from ..ndarray.ndarray import _unwrap
from . import _trace

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's shape is still unknown at data() time."""


def _shape_complete(shape) -> bool:
    return shape is not None and len(shape) >= 0 and all(
        isinstance(s, (int, onp.integer)) and s > 0 for s in shape)


class Parameter:
    """A weight/bias/aux-state tensor with lazy (deferred) initialization.

    Reference: ``gluon.Parameter`` — holds one replica per Context, a grad
    buffer per replica when ``grad_req != 'null'``, and supports deferred
    shape inference (shape dims of 0 are unknown until the first forward).
    """

    def __init__(self, name: str, grad_req: str = "write", shape=None,
                 dtype="float32", lr_mult: float = 1.0, wd_mult: float = 1.0,
                 init=None, allow_deferred_init: bool = False,
                 differentiable: bool = True, stype: str = "default",
                 grad_stype: str = "default"):
        self._name = name
        self._grad_req = None
        self._data: Optional[Dict[Context, NDArray]] = None
        self._grad: Optional[Dict[Context, NDArray]] = None
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self._stype = stype
        self._grad_stype = grad_stype
        self.grad_req = grad_req
        self._deferred_init = ()  # (init, ctx_list, default_init, data)
        self._ctx_list: Optional[List[Context]] = None
        self._var = None

    # ------------------------------------------------------------------
    @property
    def name(self) -> str:
        return self._name

    @property
    def grad_req(self) -> str:
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req: str):
        if req not in ("write", "add", "null"):
            raise ValueError(f"grad_req must be write/add/null, got {req}")
        prev, self._grad_req = self._grad_req, req
        if prev != req and self._data is not None:
            if req == "null":
                self._grad = None
            else:
                self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # Merge: unknown (0) dims take the new value; known dims must match.
        if len(self._shape) != len(new_shape):
            raise AssertionError(
                f"Expected shape {new_shape} incompatible with {self._shape}")
        merged = []
        for o, n in zip(self._shape, new_shape):
            if o and n and o != n:
                raise AssertionError(
                    f"Expected shape {new_shape} incompatible with {self._shape}")
            merged.append(o if o else n)
        self._shape = tuple(merged)

    # ------------------------------------------------------------------
    # initialization
    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit: bool = False) -> None:
        """Create replica data on ``ctx`` (reference: Parameter.initialize)."""
        if default_init is None:
            default_init = init_mod.Xavier()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        ctx = list(ctx)
        if init is None:
            init = self.init if self.init is not None else default_init
        if not _shape_complete(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                f"invalid shape: {self._shape}. Set allow_deferred_init=True "
                "or specify in_units/in_channels.")
        self._init_impl(init, ctx)

    def _init_impl(self, init, ctx_list, data=None) -> None:
        self._ctx_list = list(ctx_list)
        self._data = OrderedDict()
        if data is None:
            initializer = init_mod.create(init) if isinstance(init, str) else init
            data = NDArray(jnp.zeros(self._shape, jnp.dtype(self.dtype)),
                           ctx=self._ctx_list[0])
            initializer(init_mod.InitDesc(self.name), data)
        for ctx in self._ctx_list:
            if isinstance(data, NDArray):
                arr = data if data.context == ctx else data.copyto(ctx)
                if arr is data:
                    arr = data.copy() if len(self._ctx_list) > 1 else data
            else:
                arr = NDArray(jnp.asarray(onp.asarray(data), jnp.dtype(self.dtype)), ctx=ctx)
            if str(arr.dtype) != str(jnp.dtype(self.dtype)):
                arr._data = arr._data.astype(jnp.dtype(self.dtype))
            self._data[ctx] = arr
        self._deferred_init = ()
        if self.grad_req != "null":
            self._init_grad()

    def _init_grad(self) -> None:
        self._grad = OrderedDict()
        for ctx, arr in self._data.items():
            g = NDArray(jnp.zeros(arr.shape, arr._data.dtype), ctx=ctx)
            self._grad[ctx] = g
            arr._grad = g
            arr._grad_req = self.grad_req

    def _finish_deferred_init(self) -> None:
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        if not _shape_complete(self._shape):
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has unknown shape {self._shape} and "
                "shape inference did not resolve it.")
        self._init_impl(init if init is not None else default_init, ctx, data)

    def _load_init(self, data: NDArray, ctx, cast_dtype=False, dtype_source="current") -> None:
        """Install loaded weights (reference: Parameter._load_init)."""
        if self._shape is not None and _shape_complete(self._shape):
            if tuple(data.shape) != tuple(self._shape):
                raise AssertionError(
                    f"Failed loading Parameter '{self.name}' from saved params: "
                    f"shape incompatible expected {self._shape} vs saved {data.shape}")
        else:
            self._shape = tuple(data.shape)
        if cast_dtype and dtype_source == "current":
            data = data.astype(self.dtype)
        else:
            self.dtype = str(data.dtype)
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is None:
            if self._deferred_init:
                ctx = self._deferred_init[1]
            self._init_impl(None, ctx or [current_context()], data=data)
        else:
            self.set_data(data)

    # ------------------------------------------------------------------
    # access
    # ------------------------------------------------------------------
    def _check_and_get(self, arr_dict, ctx):
        if arr_dict is not None:
            if ctx is list:
                return list(arr_dict.values())
            if ctx is None:
                if len(arr_dict) == 1:
                    return next(iter(arr_dict.values()))
                ctx = current_context()
            if ctx in arr_dict:
                return arr_dict[ctx]
            # device-type match (cpu(0) vs cpu(0) different objects already
            # handled by Context __eq__/__hash__); fall back to any replica
            # with the same device type.
            for c, v in arr_dict.items():
                if c.device_type == getattr(ctx, "device_type", None):
                    return v
            raise RuntimeError(
                f"Parameter '{self.name}' was not initialized on context {ctx}. "
                f"It was only initialized on {list(arr_dict)}.")
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass.")
        raise RuntimeError(
            f"Parameter '{self.name}' has not been initialized. You should "
            "initialize parameters and create a Trainer first, then use "
            ".data()/.grad() to access them.")

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        scope = _trace.current()
        if scope is not None:
            proxy = scope.lookup(self)
            if proxy is not None:
                return proxy
        return self._check_and_get(self._data, ctx)

    def list_data(self) -> List[NDArray]:
        return self._check_and_get(self._data, list)

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        return self._check_and_get(self._grad, ctx)

    def list_grad(self) -> List[NDArray]:
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        return self._check_and_get(self._grad, list)

    def list_ctx(self) -> List[Context]:
        if self._data is None:
            if self._deferred_init:
                return list(self._deferred_init[1])
            raise RuntimeError(f"Parameter '{self.name}' has not been initialized")
        return list(self._data.keys())

    def set_data(self, data) -> None:
        """Set this parameter's value on all contexts."""
        self.shape = tuple(data.shape)
        if self._data is None:
            if not self._deferred_init:
                raise RuntimeError(
                    f"Parameter '{self.name}' has not been initialized")
            self._deferred_init = self._deferred_init[:3] + (data,)
            return
        for ctx, arr in self._data.items():
            val = _unwrap(data)
            arr._data = jnp.asarray(val, arr._data.dtype) if not hasattr(val, "devices") else val.astype(arr._data.dtype)
            arr._version += 1

    def _deposit_aux(self, value, ctx: Optional[Context] = None) -> None:
        """Trace-aware aux-state write (BatchNorm running stats).

        Eagerly: in-place update of the replica on ``ctx``. Under an active
        HybridBlock trace: recorded as a functional output and deposited with
        a concrete value after the compiled call (see gluon/_trace.py).
        """
        scope = _trace.current()
        val = _unwrap(value)
        if scope is not None and scope.lookup(self) is not None:
            scope.record_effect(self, ctx, val)
            return
        arr = self._check_and_get(self._data, ctx)
        arr._data = jnp.asarray(val, arr._data.dtype)
        arr._version += 1

    def zero_grad(self) -> None:
        if self._grad is None:
            return
        for g in self._grad.values():
            g._data = jnp.zeros_like(g._data)
            g._version += 1

    def reset_ctx(self, ctx) -> None:
        if isinstance(ctx, Context):
            ctx = [ctx]
        ctx = list(ctx)
        if self._data is not None:
            data = next(iter(self._data.values()))
            with _no_trace():
                self._init_impl(None, ctx, data=data)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(f"Cannot reset context for Parameter '{self.name}' "
                             "because it has not been initialized.")

    def cast(self, dtype) -> None:
        self.dtype = str(jnp.dtype(dtype))
        if self._data is None:
            return
        for arr in self._data.values():
            arr._data = arr._data.astype(jnp.dtype(dtype))
            arr._version += 1
        if self._grad is not None:
            for g in self._grad.values():
                g._data = g._data.astype(jnp.dtype(dtype))
                g._version += 1

    def var(self):
        """Symbol variable for this parameter (symbolic API parity)."""
        if self._var is None:
            from ..symbol import var
            self._var = var(self.name, shape=self.shape, dtype=self.dtype)
        return self._var

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class _no_trace:
    def __enter__(self):
        self._saved = _trace._STATE.stack
        _trace._STATE.stack = []

    def __exit__(self, *exc):
        _trace._STATE.stack = self._saved


class Constant(Parameter):
    """A constant parameter: never updated by the Trainer.

    Reference: ``gluon.Constant`` — grad_req='null', value fixed at build.
    """

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(onp.asarray(value)))
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(slf, _, arr):
                arr[:] = onp.asarray(value.asnumpy())

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype), init=_CInit())


class ParameterDict:
    """A prefix-scoped dictionary of Parameters (reference: ParameterDict)."""

    def __init__(self, prefix: str = "", shared: Optional["ParameterDict"] = None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    # -- mapping protocol --------------------------------------------------
    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self) -> str:
        return self._prefix

    def _get_impl(self, name) -> Optional[Parameter]:
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name: str, **kwargs) -> Parameter:
        """Get-or-create ``prefix+name`` (reference: ParameterDict.get)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    if k == "shape" and v is not None:
                        param.shape = v
                    elif k == "dtype" and str(getattr(param, k)) != str(v):
                        raise AssertionError(
                            f"Cannot retrieve Parameter '{name}' because desired "
                            f"attribute does not match with stored for attribute {k}")
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name: str, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'.")
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None and not isinstance(param, Constant):
            raise AssertionError(f"Parameter '{name}' already exists but is not a constant.")
        return param

    def update(self, other: "ParameterDict") -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"Cannot update self with other because they "
                                 f"have different Parameters with the same name '{k}'")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose: bool = False,
                   force_reinit: bool = False) -> None:
        if init is None:
            init = init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx) -> None:
        for v in self.values():
            v.reset_ctx(ctx)

    def list_ctx(self):
        s = []
        for v in self.values():
            for c in v.list_ctx():
                if c not in s:
                    s.append(c)
        return s

    def setattr(self, name: str, value) -> None:
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename: str, strip_prefix: str = "") -> None:
        from .. import ndarray as nd
        arg_dict = {}
        for param in self.values():
            weight = param._check_and_get(param._data, None) if param._data else None
            if weight is None and param._deferred_init:
                raise RuntimeError(f"Parameter '{param.name}' is deferred-initialized; "
                                   "run a forward pass before saving")
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = weight
        nd.save(filename, arg_dict)

    def load(self, filename: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = "",
             cast_dtype: bool = False, dtype_source: str = "current") -> None:
        from .. import ndarray as nd
        loaded = nd.load(filename)
        arg_dict = {(restore_prefix + k.split(":", 1)[-1]): v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise AssertionError(f"Parameter '{name}' is missing in file '{filename}'")
        for name, data in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise AssertionError(f"Parameter '{name}' loaded from file "
                                         f"'{filename}' is not present in this dict")
                continue
            self[name]._load_init(data, ctx, cast_dtype=cast_dtype, dtype_source=dtype_source)

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self.values())
        return f"{type(self).__name__} '{self._prefix}' (\n{s}\n)"

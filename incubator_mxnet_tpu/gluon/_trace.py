"""Trace-state shared between Parameter and the HybridBlock cached op.

The reference's ``CachedOp`` (src/imperative/cached_op.cc) re-executes a
captured nnvm graph whose inputs include every descendant parameter. Our
counterpart is a ``jax.jit``-compiled pure function; while it is being traced
we must

- substitute tracer-valued proxies for every ``Parameter.data()`` fetch
  (otherwise parameter values get baked into the compiled executable as
  constants and optimizer updates would be invisible), and
- capture aux-state writes (BatchNorm running stats — the reference mutates
  aux NDArrays inside the op) as *functional outputs* of the traced function,
  to be deposited into the real parameters with concrete values after the
  compiled call returns.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class _TraceState(threading.local):
    def __init__(self):
        self.stack: List["TraceScope"] = []


_STATE = _TraceState()


class TraceScope:
    """Active while a HybridBlock cache is being traced under jax.jit."""

    def __init__(self, overrides: Dict[int, Any]):
        # id(Parameter) -> proxy NDArray (tracer-valued)
        self.overrides = overrides
        # aux-state effects: parallel lists of (param, ctx) and traced values
        self.effect_keys: List[Tuple[Any, Any]] = []
        self.effect_values: List[Any] = []

    def __enter__(self):
        _STATE.stack.append(self)
        return self

    def __exit__(self, *exc):
        _STATE.stack.pop()

    def lookup(self, param) -> Optional[Any]:
        return self.overrides.get(id(param))

    def record_effect(self, param, ctx, value) -> None:
        key = (param, ctx)
        for i, k in enumerate(self.effect_keys):
            if k[0] is param and k[1] == ctx:
                self.effect_values[i] = value
                return
        self.effect_keys.append(key)
        self.effect_values.append(value)


def current() -> Optional[TraceScope]:
    return _STATE.stack[-1] if _STATE.stack else None


def tracing() -> bool:
    return bool(_STATE.stack)

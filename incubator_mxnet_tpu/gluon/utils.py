"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import hashlib
import os
from typing import List, Optional

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    """Split along ``batch_axis`` into ``num_slice`` pieces (reference:
    gluon.utils.split_data — the data-parallel batch splitter)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch size "
            f"that's a multiple of {num_slice} or set even_split=False.")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list: List[Context], batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Split a batch and load each slice onto one context (reference:
    gluon.utils.split_and_load — SURVEY §2.5 single-process DP)."""
    if not isinstance(data, NDArray):
        data = NDArray(jnp.asarray(onp.asarray(data)), ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: List[NDArray], max_norm: float,
                     check_isfinite: bool = True) -> float:
    """Rescale arrays so the joint L2 norm is at most ``max_norm``
    (reference: gluon.utils.clip_global_norm)."""
    if not arrays:
        raise ValueError("arrays must not be empty")
    total = sum(float(jnp.sum(jnp.square(a._data.astype(jnp.float32)))) for a in arrays)
    total_norm = total ** 0.5
    if check_isfinite and not onp.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._data = a._data * jnp.asarray(scale, a._data.dtype)
            a._version += 1
    return total_norm


def check_sha1(filename: str, sha1_hash: str) -> bool:
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url: str, path: Optional[str] = None, overwrite: bool = False,
             sha1_hash: Optional[str] = None, retries: int = 5,
             verify_ssl: bool = True) -> str:
    """Download a file (reference: gluon.utils.download). This environment
    has no network egress; only pre-existing files are honored."""
    fname = path if path and not os.path.isdir(path) else os.path.join(
        path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        f"download({url}) unavailable: no network egress in this "
        f"environment and {fname} does not exist locally.")

"""Loss zoo (reference: python/mxnet/gluon/loss.py — SURVEY §2.8)."""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from .block import HybridBlock

__all__ = [
    "Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
    "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss", "CTCLoss",
    "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
    "TripletLoss", "PoissonNLLLoss", "CosineEmbeddingLoss", "SDMLLoss",
]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight) if hasattr(F, "broadcast_mul") else loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base: weighted, per-sample-mean loss (reference: gluon.loss.Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


def _mean_all_but_batch(F, loss, batch_axis):
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    if not axes:
        return loss
    return F.mean(loss, axis=axes)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + F.Activation(
                    -F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = (pred - pred * label + log_weight *
                        (F.Activation(-F.abs(pred), act_type="softrelu") + F.relu(-pred)))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label + F.log(1.0 - pred + eps) * (1.0 - label))
            else:
                loss = -(F.log(pred + eps) * label * pos_weight
                         + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE fused (reference: gluon.loss.SoftmaxCrossEntropyLoss).
    Sparse labels (class index) by default, dense when sparse_label=False."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if str(pred.dtype) in ("bfloat16", "float16"):
            # CE over a large vocab needs fp32 log-softmax — bf16 logits
            # carry ~3 decimal digits; the cast fuses into the same kernel
            pred = pred.astype("float32")
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference: src/operator/
    contrib/ctc_loss.cc → here the pure-JAX dynamic-program op)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        if layout not in ("NTC", "TNC"):
            raise ValueError(f"unsupported layout {layout}")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None, label_lengths=None,
                       sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, dim1=0, dim2=1)
        loss = F.CTCLoss(pred, label, pred_lengths, label_lengths)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise ValueError(f"unsupported label_format {label_format}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + F.Activation(
            -F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        axes = tuple(range(1, pred.ndim))
        loss = (F.sum(F.square(positive - pred), axis=axes)
                - F.sum(F.square(negative - pred), axis=axes) + self._margin)
        loss = F.relu(loss)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, label, sample_weight=None, epsilon=1e-08):
        label = _reshape_like(F, label, pred)
        if self._from_logits:
            loss = F.exp(pred) - label * pred
        else:
            loss = pred - label * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling approximation of log(label!)
            stirling = (label * F.log(label + epsilon) - label
                        + 0.5 * F.log(2 * onp.pi * (label + epsilon)))
            stirling = F.where(label <= 1, F.zeros_like(stirling), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos = (F.sum(input1 * input2, axis=-1)
               / (F.norm(input1, axis=-1) * F.norm(input2, axis=-1) + 1e-12))
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, 1.0 - cos, F.relu(cos - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference: gluon/loss.py SDMLLoss):
    batchwise smoothed-CE over the pairwise SQUARED-euclidean-distance matrix
    of two embedding batches — row i's positive is column i, every other
    column a negative (the reference's _compute_distances uses squared
    distances; no sqrt)."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._sp = smoothing_parameter

    def hybrid_forward(self, F, x1, x2, sample_weight=None):
        n = x1.shape[0]
        sq1 = F.sum(F.square(x1), axis=1, keepdims=True)          # (N, 1)
        sq2 = F.sum(F.square(x2), axis=1, keepdims=True)          # (N, 1)
        d2 = sq1 + F.transpose(sq2) - 2.0 * F.dot(x1, x2, transpose_b=True)
        logp = F.log_softmax(-d2, axis=-1)
        eye = F.eye(n)
        smoothed = ((1.0 - self._sp) * eye
                    + (self._sp / max(n - 1, 1)) * (1.0 - eye))
        loss = -F.sum(smoothed * logp, axis=-1)
        return _apply_weighting(F, loss, self._weight, sample_weight)

"""Training monitor: per-batch statistics of intermediate outputs.

Reference counterpart: ``python/mxnet/monitor.py (Monitor)`` — installed on
executors (``mod.fit(..., monitor=mon)``), it records a statistic of every
op output whose name matches ``pattern`` each ``interval`` batches. The
reference hooks the engine's per-op callbacks; here the Executor compiles a
second "capture" program returning every node's primary output (one extra
jit executable, built lazily on the first monitored batch — the normal
training step stays a single fused program).

Usage::

    mon = mx.monitor.Monitor(interval=10, pattern='.*fullyconnected.*')
    mod.fit(train_iter, monitor=mon, ...)
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

import numpy as onp

__all__ = ["Monitor"]


def _default_stat(arr: onp.ndarray) -> float:
    """Reference default: ||x|| / sqrt(x.size)."""
    a = onp.asarray(arr, dtype=onp.float64)
    return float(onp.linalg.norm(a) / max(onp.sqrt(a.size), 1.0))


class Monitor:
    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, float]] = []
        self.logger = logging.getLogger(__name__)
        self._execs: List = []

    # -- executor side -----------------------------------------------------
    def install(self, exe) -> None:
        """Attach to an Executor (called by Module.bind/fit)."""
        if exe not in self._execs:
            self._execs.append(exe)

    def tic(self) -> None:
        """Start of batch: decide whether this batch is monitored."""
        self.activated = (self.step % self.interval) == 0
        self.step += 1

    def _collect(self) -> None:
        for exe in self._execs:
            for name, val in exe.capture_internals().items():
                if not self.pattern.match(name):
                    continue
                self.queue.append(
                    (self.step - 1, name, self.stat_func(onp.asarray(val))))

    def toc(self) -> List[Tuple[int, str, float]]:
        """End of batch: collect stats from installed executors (if this
        batch was monitored) and return them."""
        if not self.activated:
            return []
        self._collect()
        self.activated = False
        res, self.queue = self.queue, []
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        return res

    def toc_print(self) -> None:
        for step, name, stat in self.toc():
            self.logger.info("Batch: %7d %30s %g", step, name, stat)

"""Training monitor: per-batch statistics of intermediate outputs.

Reference counterpart: ``python/mxnet/monitor.py (Monitor)`` — installed on
executors (``mod.fit(..., monitor=mon)``), it records a statistic of every
op output whose name matches ``pattern`` each ``interval`` batches.

.. deprecated:: this class is now a COMPATIBILITY BRIDGE onto
   ``mx.telemetry.numerics``. The reference design (and this module's
   previous life) re-executed a second "capture" program per monitored
   batch and pulled every intermediate to host — on the jit runtime that
   breaks whole-step capture (two executables per step) and is exactly
   the per-step host-readback anti-pattern MX603/MX701 forbid. The
   bridge instead *taps* each matching child block
   (``numerics.tap(name, out)`` via a forward hook, collected at trace
   time), so the statistics are computed **inside** the one compiled
   step and decimated host-side — MXNet-parity users get the new
   telemetry (events, gauges, drift watchdog, flight bundles) for free.
   New code should use ``telemetry.numerics`` directly.

Usage (bridge)::

    mon = mx.monitor.Monitor(interval=10, pattern='.*dense.*')
    mon.install(net)                    # BEFORE the first trainer.step
    trainer = parallel.ShardedTrainer(net, ...)
    for x, y in batches:
        mon.tic()
        trainer.step(x, y)
        mon.toc_print()                 # rms rows from the numerics ring

The legacy executor path (``install(exe)`` with an object exposing
``capture_internals``) keeps its eager behavior for Module users.
"""
from __future__ import annotations

import logging
import re
import warnings
from typing import Callable, List, Optional, Tuple

import numpy as onp

__all__ = ["Monitor"]


def _default_stat(arr: onp.ndarray) -> float:
    """Reference default: ||x|| / sqrt(x.size) — exactly the ``rms``
    field of the numerics stat vector, which is why the bridge can
    serve it without ever materializing the tensor on host."""
    a = onp.asarray(arr, dtype=onp.float64)
    return float(onp.linalg.norm(a) / max(onp.sqrt(a.size), 1.0))


class Monitor:
    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, float]] = []
        self.logger = logging.getLogger(__name__)
        self._execs: List = []
        self._tap_sites: List[str] = []
        self._hook_handles: List = []
        self._reported: dict = {}      # site -> last ring step reported
        self._set_override = False     # we armed numerics.configure()

    # -- install: numerics bridge (Block) or legacy executor ---------------
    def install(self, target) -> None:
        """Attach to an Executor (legacy eager path) or to a gluon Block
        tree (the numerics bridge: every matching child is tapped via a
        forward hook, so its output statistics are computed in-graph by
        the instrumented trainer/serving build)."""
        if hasattr(target, "capture_internals"):
            if target not in self._execs:
                self._execs.append(target)
            return
        from .gluon.block import Block
        if not isinstance(target, Block):
            raise TypeError(f"Monitor.install expects an Executor or a "
                            f"gluon Block, got {type(target)}")
        warnings.warn(
            "mx.monitor.Monitor now bridges onto mx.telemetry.numerics "
            "(in-graph stats, decimated host-side); use the numerics "
            "API directly in new code", DeprecationWarning, stacklevel=2)
        from .telemetry import numerics as _numerics
        # the bridge needs numerics ON: if the env left it off, arm a
        # summary config whose decimation matches this monitor's
        # interval — installed via the programmatic override so the
        # NEXT trainer/serve build picks it up
        cfg = _numerics.config()
        if not cfg.enabled:
            _numerics.configure(_numerics.NumericsConfig(
                mode="summary", every=self.interval))
            self._set_override = True
        self._install_block(target)

    def _install_block(self, block) -> None:
        from .telemetry import numerics as _numerics
        seen = set()

        def _walk(b):
            yield b
            for c in b._children.values():
                yield from _walk(c)

        for child in _walk(block):
            name = getattr(child, "name", None)
            if not name or name in seen or child is block:
                continue
            seen.add(name)
            if not self.pattern.match(name):
                continue

            def hook(blk, _args, out, _name=name):
                o = out[0] if isinstance(out, (list, tuple)) else out
                _numerics.tap(_name, o)

            self._hook_handles.append(child.register_forward_hook(hook))
            self._tap_sites.append(f"act:{name}")
        if not self._tap_sites:
            self.logger.warning(
                "Monitor.install: no child block matched pattern %r",
                self.pattern.pattern)

    def detach(self) -> None:
        """Remove every bridge hook (the taps disappear from the NEXT
        trace; already-compiled graphs keep their baked stats) and
        restore the numerics config override if :meth:`install` armed
        it — a detached monitor must not leave every LATER-built
        trainer/CompiledModel silently instrumented."""
        for h in self._hook_handles:
            h.detach()
        self._hook_handles = []
        if self._set_override:
            from .telemetry import numerics as _numerics
            _numerics.configure(None)
            self._set_override = False

    # -- batch cadence ------------------------------------------------------
    def tic(self) -> None:
        """Start of batch: decide whether this batch is monitored."""
        self.activated = (self.step % self.interval) == 0
        self.step += 1

    def _collect(self) -> None:
        for exe in self._execs:
            for name, val in exe.capture_internals().items():
                if not self.pattern.match(name):
                    continue
                self.queue.append(
                    (self.step - 1, name, self.stat_func(onp.asarray(val))))
        if self._tap_sites:
            from .telemetry import numerics as _numerics
            if self.stat_func is not _default_stat:
                warnings.warn(
                    "Monitor bridge: custom stat_func is not supported "
                    "over in-graph stats; reporting rms (the reference "
                    "default norm/sqrt(size))", stacklevel=2)
                self.stat_func = _default_stat
            for site in self._tap_sites:
                for rec in _numerics.ring(site):
                    if rec["step"] is None \
                            or rec["step"] <= self._reported.get(site, 0):
                        continue
                    self._reported[site] = rec["step"]
                    self.queue.append((rec["step"], site, rec["rms"]))

    def toc(self) -> List[Tuple[int, str, float]]:
        """End of batch: collect stats (legacy executors eagerly; bridge
        sites from the numerics ring — new entries since last toc) and
        return them."""
        if not self.activated:
            return []
        self._collect()
        self.activated = False
        res, self.queue = self.queue, []
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        return res

    def toc_print(self) -> None:
        for step, name, stat in self.toc():
            self.logger.info("Batch: %7d %30s %g", step, name, stat)

"""Misc utilities (reference: python/mxnet/util.py + dmlc::GetEnv plane).

The env-var catalog (SURVEY §5.6) is centralized here: every runtime knob the
framework reads goes through :func:`getenv` with its default, and
:func:`env_var_doc` renders the ``env_var.md``-style table.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional

__all__ = ["getenv", "setenv", "env_var_doc", "makedirs", "use_np_shape",
           "is_np_shape", "is_np_array", "set_np", "reset_np", "np_shape",
           "nearest_rank_percentile", "parse_size", "hbm_budget_bytes",
           "peak_tflops", "roofline_peaks", "PEAK_TFLOPS_BY_KIND"]


def parse_size(s: str) -> int:
    """Byte-size string → int bytes: plain/float forms (``"123"``,
    ``"16e9"``) and binary suffixes (``"512M"``, ``"16G"``, ``"1.5T"``,
    optional trailing ``B``/``iB``). THE parse ``MXTPU_HBM_BUDGET``
    consumers share (the MX709 pass, the serve staging preflight, the
    autotune feasibility constraint, the memory ledger)."""
    mult = 1
    low = str(s).strip().lower()
    # strip an optional iB/B after a unit letter, then the unit letter
    if low.endswith("ib"):
        low = low[:-2]
    elif low.endswith("b"):
        low = low[:-1]
    if low and low[-1] in "kmgt":
        mult = {"k": 1 << 10, "m": 1 << 20,
                "g": 1 << 30, "t": 1 << 40}[low[-1]]
        low = low[:-1]
    try:
        if not low:               # suffix-only input ("B", "iB", "G", " ")
            raise ValueError(low)
        return int(float(low) * mult)
    except ValueError:
        raise ValueError(f"cannot parse byte size {s!r} (want e.g. "
                         "'2000000000', '16e9', '512M', '16G')") from None


def hbm_budget_bytes() -> Optional[int]:
    """``MXTPU_HBM_BUDGET`` parsed to bytes via :func:`parse_size`, or
    ``None`` when unset — THE single budget read shared by the MX709
    static pass (``analysis.hlo.cost``), the serve staging preflight,
    the autotune feasibility constraint, and the ``telemetry.memory``
    ledger, so the gates can never read different capacities."""
    raw = getenv("MXTPU_HBM_BUDGET")
    return parse_size(raw) if raw else None


#: nominal per-chip bf16 peaks for MFU/roofline accounting (public
#: specs) — THE single table ``bench.py``, ``benchmark/autotune.py``,
#: and ``telemetry.goodput`` all read, so a chip-kind correction lands
#: in every consumer at once. The unknown/CPU default keeps
#: device-blind runs deterministic (rankings, not absolute MFU).
PEAK_TFLOPS_BY_KIND = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
                       "v5": 459.0, "v4": 275.0, "v3": 123.0,
                       "v6e": 918.0, "v6 lite": 918.0, "trillium": 918.0}
DEFAULT_PEAK_TFLOPS = 459.0
DEFAULT_PEAK_GBPS = 1200.0       # nominal HBM bandwidth
DEFAULT_ICI_GBPS = 90.0          # nominal inter-chip bandwidth


def peak_tflops() -> float:
    """Per-chip bf16 peak TFLOPs (``MXTPU_PEAK_TFLOPS`` overrides, else
    by device kind; the deterministic default on unknown/CPU/no
    backend)."""
    env = os.environ.get("MXTPU_PEAK_TFLOPS")
    if env:
        return float(env)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
        return next((v for k, v in PEAK_TFLOPS_BY_KIND.items()
                     if k in kind), DEFAULT_PEAK_TFLOPS)
    except Exception:  # noqa: BLE001 — no backend: stay deterministic
        return DEFAULT_PEAK_TFLOPS


def roofline_peaks() -> tuple:
    """``(peak_flops_per_s, hbm_bytes_per_s, ici_bytes_per_s)`` — the
    roofline denominators (``MXTPU_PEAK_TFLOPS`` / ``MXTPU_PEAK_GBPS``
    / ``MXTPU_ICI_GBPS`` override the per-kind defaults)."""
    bw = float(os.environ.get("MXTPU_PEAK_GBPS", DEFAULT_PEAK_GBPS))
    ici = float(os.environ.get("MXTPU_ICI_GBPS", DEFAULT_ICI_GBPS))
    return peak_tflops() * 1e12, bw * 1e9, ici * 1e9


def nearest_rank_percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list — THE
    shared kernel for every host-side latency summary (``metric.
    Percentile``, the ``profiler`` span recorder). Returns NaN on empty."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]

#: name -> (default, description). The single catalog, reference
#: docs/static_site/src/pages/api/faq/env_var.md.
ENV_VARS: Dict[str, tuple] = {
    "MXNET_ENGINE_TYPE": ("XLA", "Execution engine; XLA async dispatch "
                          "replaces ThreadedEnginePerDevice. 'Naive' maps to "
                          "jax.disable_jit debugging."),
    "MXNET_ENFORCE_DETERMINISM": ("0", "Request deterministic XLA lowering."),
    "MXNET_USE_FUSION": ("1", "XLA fusion is always on; kept for parity."),
    "MXNET_GPU_MEM_POOL_RESERVE": ("0", "PjRt manages HBM pooling."),
    "MXNET_KVSTORE_BIGARRAY_BOUND": ("1000000", "Kept for parity; sharding "
                                     "rules make the layout decision."),
    "MXTPU_KVSTORE_FALLBACK": ("0", "1 opts into the per-parameter Python "
                               "kvstore push/pull loop (the async-PS "
                               "scenario): ShardedTrainer.step exchanges "
                               "gradients host-side per key with the "
                               "store client's retry/exactly-once "
                               "semantics intact. Default 0: gradient "
                               "exchange is compiled XLA collectives — "
                               "the pjit step (ShardedTrainer) or one "
                               "batched store collective (gluon.Trainer)."),
    "MXNET_TEST_SEED": ("", "Fix the test RNG seed."),
    "MXTPU_SERVE_DEADLINE_MS": ("5", "Max milliseconds the oldest queued "
                                "request waits before the serve "
                                "DynamicBatcher flushes a partial batch."),
    "MXTPU_SERVE_QUEUE_LIMIT": ("1024", "Bounded serve request-queue size; "
                                "a full queue rejects submits "
                                "(backpressure, QueueFullError)."),
    "MXTPU_SERVE_MAX_BATCH": ("0", "Cap on the coalesced serve batch size; "
                              "0 = the bucket table's largest batch "
                              "bucket."),
    "MXTPU_SERVE_BENCH_MODEL": ("mlp", "serve_bench workload "
                                "(mlp|lenet|bert)."),
    "MXTPU_SERVE_BENCH_N": ("1000", "serve_bench dynamic-section request "
                            "count."),
    "MXTPU_SERVE_REQUEST_TIMEOUT_S": ("30", "Per-request deadline: the "
                                      "TCP front end and the HA router "
                                      "wait this long for a result, then "
                                      "return a structured "
                                      "deadline_exceeded reply with "
                                      "retry_after instead of a bare "
                                      "exception."),
    "MXTPU_SERVE_HEARTBEAT_MS": ("100", "Router health-check interval: "
                                 "each sweep probes every replica's "
                                 "state, queue depth and flush "
                                 "progress."),
    "MXTPU_SERVE_STALL_S": ("2", "Queued requests with zero flush "
                            "progress for this long mark a replica "
                            "wedged — it is killed and restarted by the "
                            "router's health loop."),
    "MXTPU_SERVE_RETRIES": ("2", "Failover retries per idempotent "
                            "request: each retry moves to a surviving "
                            "replica with capped exponential backoff; "
                            "exhaustion sheds explicitly with "
                            "retry_after."),
    "MXTPU_SERVE_RETRY_BACKOFF_MS": ("10", "Base backoff between router "
                                     "failover retries (doubles per "
                                     "attempt, capped at 200 ms, never "
                                     "past the request deadline)."),
    "MXTPU_SERVE_HEDGE_MS": ("0", "After this many ms without a result "
                             "the router races ONE hedged duplicate on "
                             "a second healthy replica (first result "
                             "wins); 0 disables hedging."),
    "MXTPU_SERVE_SHED_DEPTH": ("0", "Overload shedding: when EVERY "
                               "healthy replica's queue is at/over this "
                               "depth, new requests are rejected with "
                               "retry_after instead of queueing; 0 "
                               "disables (per-replica backpressure "
                               "still applies)."),
    "MXTPU_SERVE_TENANT_INFLIGHT": ("0", "Per-tenant admission cap: "
                                    "concurrent router requests a single "
                                    "tenant may hold before being shed "
                                    "with retry_after; 0 = unlimited."),
    "MXTPU_SERVE_TENANT_TOKENS_PER_S": ("0", "Per-tenant decode QoS: "
                                        "sustained generated-tokens/sec "
                                        "budget (token bucket); requests "
                                        "whose estimated tokens would "
                                        "breach it are shed with "
                                        "retry_after BEFORE queueing; "
                                        "0 = unlimited."),
    "MXTPU_SERVE_TENANT_TOKEN_BURST": ("0", "Token-bucket burst depth for "
                                       "MXTPU_SERVE_TENANT_TOKENS_PER_S "
                                       "(tokens); 0 = one second's "
                                       "budget."),
    "MXTPU_DECODE_MAX_BATCH": ("8", "Decode batch rows: concurrent "
                               "sequences one DecodeEngine steps per "
                               "token boundary (the fixed shape of the "
                               "AOT decode executable)."),
    "MXTPU_DECODE_BLOCK_SIZE": ("16", "Tokens per paged-KV-cache page; "
                                "pages are the allocation unit of the "
                                "decode block pool."),
    "MXTPU_DECODE_MAX_TOKENS": ("64", "Generation cap per sequence = "
                                "pages-per-sequence x block size; must "
                                "fit the model's position table."),
    "MXTPU_DECODE_QUEUE_LIMIT": ("256", "Bounded decode request-queue "
                                 "size; past it submit() sheds with "
                                 "QueueFullError (backpressure)."),
    "MXTPU_DECODE_MAX_REQUEUES": ("3", "Cache-pressure admissions bounce "
                                  "back to the queue at most this many "
                                  "times before the stream is shed with "
                                  "CacheExhausted."),
    "MXTPU_BENCH_MODEL": ("bert_12_768_12", "bench.py model config."),
    "MXTPU_BENCH_TRACE": ("", "bench.py: capture one profiled step into this "
                          "directory (jax.profiler trace)."),
    "MXTPU_BENCH_RETRIES": ("1", "bench.py device-init watchdog: extra "
                            "bounded windows granted after the first "
                            "MXTPU_BENCH_TIMEOUT expiry before aborting "
                            "with rc=75 (0 = abort on the first expiry). "
                            "The abort record's 'attempts' field counts "
                            "the windows waited."),
    "MXTPU_BENCH_RETRY_BACKOFF_S": ("60", "Seconds ADDED to the watchdog "
                                    "budget for each retry window — a "
                                    "pool grant that lands late becomes "
                                    "a recovered round, not a blind "
                                    "one."),
    "MXTPU_PEAK_TFLOPS": ("", "Override per-chip peak for MFU accounting."),
    "MXTPU_FLASH_ATTENTION": ("1", "Enable the Pallas flash-attention path."),
    "MXTPU_FLASH_BK": ("", "Flash-attention key/value block size override "
                       "(ops/pallas/flash_attention.py); unset = "
                       "auto-sized per sequence length. An autotune "
                       "dimension: benchmark/autotune.py sweeps it and "
                       "banked winners apply it at build time."),
    "MXTPU_FLASH_BQ": ("", "Flash-attention query block size override; "
                       "unset = auto-sized. Autotune dimension like "
                       "MXTPU_FLASH_BK."),
    "MXTPU_EMBED_ONEHOT_GRAD": ("0", "Embedding weight gradient as a one-hot "
                                "MXU matmul instead of scatter-add (sweep "
                                "candidate; numerically identical)."),
    "MXTPU_FUSED_STEP": ("1", "Whole-step capture (ShardedTrainer): the "
                         "guard finite verdict and the LR-schedule "
                         "position are computed INSIDE the one donated "
                         "pjit step — a guarded, scheduled step runs "
                         "exactly one jitted graph with one host sync. "
                         "0 restores the unfused shape (separate jitted "
                         "finite check, per-step host LR eval + "
                         "transfer) for A/B probes and bit-parity "
                         "tests."),
    "MXTPU_AUTOTUNE_DIR": ("", "On-disk autotune cache root. When set, "
                           "ShardedTrainer and serve.CompiledModel "
                           "consult it at build time and overlay the "
                           "banked winner's env knobs (flash block "
                           "sizes, embed-grad path) for exactly the "
                           "trace/compile scope; explicitly user-set "
                           "variables always win. Unset = no consult "
                           "(one env read on the build path)."),
    "MXTPU_AUTOTUNE": ("1", "0 disables autotune-cache consults even "
                       "when MXTPU_AUTOTUNE_DIR is set (kill switch "
                       "for debugging a suspect banked winner)."),
    "MXTPU_AUTOTUNE_BUDGET": ("16", "Default candidate budget per family "
                              "for benchmark/autotune.py when --budget "
                              "is not given (candidates enumerate in "
                              "deterministic space order and truncate "
                              "here)."),
    "MXTPU_QUANT_PERCENTILE": ("99.99", "Calibration percentile the "
                               "quantization Observer paths use when no "
                               "explicit percentile is passed "
                               "(quantization.quantize_model, "
                               "Observer.ranges, models.quantized_smoke). "
                               "100 = exact min/max (outlier-hostage "
                               "ranges); 99.99 clips the histogram tail "
                               "the TensorRT way."),
    "MXTPU_INT8_FAMILY": ("lenet", "Quantized zoo family "
                          "benchmark/int8_probe.py censuses for its "
                          "per-bucket MX71x summary (any "
                          "models.QUANT_FAMILIES member)."),
    "MXTPU_HBM_BUDGET": ("", "Per-chip device-memory budget in bytes "
                         "(K/M/G suffixes and float forms accepted). "
                         "When set: the MX709 hlo_memory pass errors on "
                         "any graph (or summed serve bucket ladder) "
                         "whose liveness-scan peak_live_bytes exceeds "
                         "it, serve.ModelRegistry.load rejects "
                         "over-budget ladders at staging while the "
                         "active version keeps serving, "
                         "benchmark/autotune.py excludes infeasible "
                         "candidates from winner election, and the "
                         "telemetry.memory ledger publishes it as "
                         "mxtpu_memory_budget_bytes / uses it as the "
                         "capacity in context.tpu_memory_info's "
                         "ledger fallback. Unset = no memory gating."),
    "MXTPU_MEMORY_SAMPLE_S": ("0", "Interval (seconds) of the "
                              "telemetry.memory background sampler "
                              "(named daemon thread mx-memory-ledger): "
                              "each tick reads jax.live_arrays() + "
                              "device memory_stats + registered site "
                              "providers into mxtpu_memory_* gauges and "
                              "runs the leak watchdog (monotonic growth "
                              "across a full 8-sample window >= 1 MiB "
                              "emits a memory.leak warning event). "
                              "0 = sampler off (manual sample() calls "
                              "still work)."),
    "MXTPU_NUMERICS": ("", "In-graph numerics telemetry "
                       "(telemetry.numerics): 'summary' makes the "
                       "trainer's pjit step and serve.CompiledModel "
                       "return per-site min/max/mean/rms/zero-fraction/"
                       "finite-fraction vectors (param:/grad:/act:/"
                       "serve.out: sites) as extra pinned outputs of "
                       "the SAME jitted graph; 'hist' additionally "
                       "accumulates log2-magnitude histograms per site "
                       "(quantization.Observer calibration tables). "
                       "Unset/other = off: the traced graphs are "
                       "byte-identical to an uninstrumented build "
                       "(the perf-proxy gate proves it). Resolved at "
                       "build time like the autotune consult."),
    "MXTPU_NUMERICS_EVERY": ("16", "Host-side decimation of numerics "
                             "stats: the stat outputs are synced (and "
                             "folded into numerics.step events, "
                             "mxtpu_numerics_* gauges, the per-site "
                             "ring) every N steps/requests, riding the "
                             "guard's existing device read — never an "
                             "extra per-step round trip."),
    "MXTPU_NUMERICS_SITES": ("", "Comma-separated fnmatch allowlist "
                             "over numerics site names (e.g. "
                             "'grad:*,act:*attn*'); empty = every "
                             "site. Filtering happens at trace time, "
                             "so excluded sites cost zero graph ops."),
    "MXTPU_NUMERICS_BINS": ("40", "Log2-magnitude histogram buckets "
                            "per site in hist mode (bucket i counts "
                            "|x| in [2^(-24+i), 2^(-24+i+1)))."),
    "MXTPU_NUMERICS_RING": ("128", "Per-site numerics history-ring "
                            "capacity (the drift watchdog's window and "
                            "the postmortem's trajectory live here)."),
    "MXTPU_NUMERICS_DRIFT": ("warn", "Drift-watchdog action: 'warn' "
                             "emits damped numerics.drift warning "
                             "events only; 'rollback' additionally "
                             "escalates a sustained drift (monotonic "
                             "rms growth / finite-fraction decay over "
                             "the recorded window) to the trainer's "
                             "StepGuard — its policy then decides "
                             "warn/skip_and_rollback/halt BEFORE the "
                             "run ever goes non-finite."),
    "MXTPU_GOODPUT": ("0", "1 enables the run-level goodput ledger "
                      "(telemetry.goodput): every wall-second between "
                      "begin() and report() is attributed to compute / "
                      "collective / input_wait / host / compile / "
                      "checkpoint / rollback_waste (unattributed is the "
                      "honesty remainder, gated <10% by the "
                      "goodput-smoke CI job), with a measured-vs-"
                      "roofline MFU headline. Host-side bookkeeping "
                      "only — the compiled graphs are untouched either "
                      "way (the perf-proxy gate proves banked "
                      "PERF_PROXY.json stays byte-identical). Default "
                      "off: the trainer/io/checkpoint hooks are one "
                      "env read."),
    "MXTPU_GOODPUT_WINDOW": ("32", "Steps per goodput attribution "
                             "window: each window closes with one "
                             "goodput.window event and refreshed "
                             "mxtpu_goodput_* gauges (share per "
                             "category, measured/predicted MFU, "
                             "divergence, unattributed share)."),
    "MXTPU_DIRECTOR": ("0", "1 enables the flight director "
                       "(telemetry.director): a closed adaptive loop "
                       "that watches goodput.window events and "
                       "hot-applies ONE allowlisted remediation per "
                       "breach — prefetch depth for input_bound, a "
                       "staged recompile (ledger site "
                       "director.recompile) for compute_bound, Router "
                       "shed/hedge for a serve SLO burn — with a "
                       "damped hysteresis (cooldown + revert-if-worse, "
                       "exactly one revert) and every decision on an "
                       "audited ring. Host-side only; default off is "
                       "one env read at install()."),
    "MXTPU_DIRECTOR_DIVERGENCE_PCT": ("25", "Flight-director trigger "
                                      "threshold: a goodput window "
                                      "whose measured-vs-roofline MFU "
                                      "divergence is at or below "
                                      "-THRESHOLD percent counts as "
                                      "breached."),
    "MXTPU_DIRECTOR_WINDOWS": ("2", "Consecutive breached (or "
                               "bucket-drifted) goodput windows "
                               "required before the director acts — "
                               "the debounce half of the hysteresis."),
    "MXTPU_DIRECTOR_COOLDOWN": ("2", "Goodput windows the director "
                                "holds after every decision before it "
                                "may act again; the first window after "
                                "the cooldown is the revert-if-worse "
                                "evaluation sample."),
    "MXTPU_DIRECTOR_REVERT_MARGIN_PCT": ("5", "Revert-if-worse margin: "
                                         "the post-cooldown window's "
                                         "divergence must be at least "
                                         "this many points below the "
                                         "pre-action baseline to "
                                         "trigger the (single) "
                                         "revert."),
    "MXTPU_DIRECTOR_RING": ("64", "Flight-director decision-ring "
                            "capacity (the audit trail embedded in "
                            "telemetry.snapshot(), flight bundles and "
                            "tools/postmortem.py)."),
    "MXTPU_DIRECTOR_MAX_DEPTH": ("8", "Cap on the PrefetchIter depth "
                                 "the director's input_bound "
                                 "remediation may grow to (doubling "
                                 "per action up to the cap)."),
    "MXTPU_DIRECTOR_BUDGET": ("4", "Candidate budget for the "
                              "director's rescored trace-only autotune "
                              "search (benchmark.autotune.search with "
                              "the measured attribution folded into "
                              "the roofline score)."),
    "MXTPU_DIRECTOR_HEDGE_MS": ("50", "Hedge deadline the director's "
                                "serve-side remediation enables on a "
                                "Router whose hedging was off when the "
                                "SLO burn fired."),
    "MXTPU_TELEMETRY": ("1", "Master switch for the mx.telemetry event "
                        "bus; 0 turns every emit() into a no-op."),
    "MXTPU_TELEMETRY_RING": ("1024", "Per-kind event ring-buffer capacity; "
                             "aggregate counts keep counting past the "
                             "ring, only raw events drop."),
    "MXTPU_TELEMETRY_JSONL": ("", "When set, every telemetry event is "
                              "appended to this file as one strict-JSON "
                              "line (rotating sink, installed on first "
                              "emission)."),
    "MXTPU_TELEMETRY_JSONL_MAX_MB": ("64", "Rotation threshold for the "
                                     "JSON-lines sink; past it the file "
                                     "moves to <path>.1 (one generation "
                                     "kept)."),
    "MXTPU_LOCKCHECK": ("0", "Runtime lock-order sanitizer: locks "
                        "created through lockcheck.make_lock become "
                        "order-tracking wrappers that flag inversions "
                        "as concurrency.inversion telemetry events "
                        "(also auto-enabled whenever MXTPU_CHAOS is "
                        "set)."),
    "MXTPU_LOCKCHECK_HOLD_MS": ("250", "Lock-hold duration past which a "
                                "tracked lock's release publishes a "
                                "concurrency.hold warning event."),
    "MXTPU_LOCKCHECK_TIMEOUT_S": ("5", "Bound on an acquire that "
                                  "crosses a recorded lock-order "
                                  "inversion; expiry raises "
                                  "LockOrderError instead of "
                                  "deadlocking the process."),
    "MXTPU_TRACE_SAMPLE": ("0.1", "Head-sampling probability for NEW "
                           "distributed traces (0..1). Unsampled traces "
                           "still propagate ids across threads and the "
                           "wire but record nothing — the serve_bench "
                           "tracing-overhead gate holds the p50 tax at "
                           "this default under 3%. CI's trace-smoke "
                           "job sets 1.0 so every request must stitch "
                           "into one rooted span tree."),
    "MXTPU_TRACE_RING": ("65536", "Completed-span ring capacity "
                         "(process-wide; oldest spans drop first)."),
    "MXTPU_FLIGHT_DIR": ("", "When set, the flight recorder writes one "
                         "atomic strict-JSON post-mortem bundle here on "
                         "watchdog trip, guard halt, replica "
                         "crash/stall-kill, and chaos crash sites; "
                         "unset = recorder off (the off path is one "
                         "env read). Render bundles with "
                         "tools/postmortem.py."),
    "MXTPU_FLIGHT_MAX": ("16", "Per-process cap on flight bundles — a "
                         "crash loop produces a few bundles, not a "
                         "full disk."),
    "MXTPU_FLIGHT_MIN_S": ("0", "Minimum seconds between two flight "
                           "bundles (storm damping; 0 = no spacing)."),
    "MXTPU_FLIGHT_SPANS": ("2048", "Most-recent trace spans included in "
                           "a flight bundle."),
    "MXTPU_COLLECTIVE_LEDGER": ("0", "Master switch for the collective-"
                                "schedule ledger (the MX9xx runtime "
                                "twin): 1/true/on/yes banks a "
                                "verb/axis-sequence fingerprint per "
                                "compiled step and crosschecks it "
                                "across the pod at dist.initialize() "
                                "and on post-warmup recompiles. Off "
                                "(default) costs one env read."),
    "MXTPU_COLLECTIVE_LEDGER_RING": ("512", "Capacity of the per-process "
                                     "dispatch ring (most-recent "
                                     "collective dispatches kept for "
                                     "flight bundles; oldest drop "
                                     "first)."),
    "MXTPU_COLLECTIVE_LEDGER_TIMEOUT_S": ("20", "Seconds each process "
                                          "waits for peer fingerprint "
                                          "blobs during a crosscheck "
                                          "before declaring the "
                                          "exchange failed."),
    "MXTPU_ELASTIC": ("0", "Master switch for the elastic multi-host "
                      "control plane (parallel.elastic): 1 starts the "
                      "heartbeat-lease daemon at dist.initialize(), so "
                      "a host that dies mid-run is a detected loss "
                      "(flight bundle + HostLossError at the next step "
                      "boundary) instead of a pod hung inside a "
                      "collective. Off costs one env read."),
    "MXTPU_ELASTIC_LEASE_S": ("10", "Heartbeat-lease validity window: a "
                              "pod member whose newest lease is older "
                              "than this is a detected host loss."),
    "MXTPU_ELASTIC_HEARTBEAT_S": ("", "Beat interval of the lease "
                                  "daemon; unset = a third of the lease "
                                  "(three missed beats expire it)."),
    "MXTPU_ELASTIC_GENERATION": ("0", "Restore-generation counter, "
                                 "stamped by the launcher on each "
                                 "elastic restart: namespaces the lease "
                                 "keys so a restarted pod never reads a "
                                 "dead generation's leases, and rides "
                                 "checkpoint meta."),
    "MXTPU_ELASTIC_COMMIT_TIMEOUT_S": ("60", "Bound on the primary's "
                                       "wait for every peer's commit "
                                       "marker during a multi-host "
                                       "checkpoint save; expiry raises "
                                       "CheckpointError naming the "
                                       "missing process indices instead "
                                       "of hanging the save."),
    "MXTPU_SLO_WINDOWS": ("60:14.4,300:6", "Burn-rate alert windows as "
                          "'seconds:threshold,...' — every window must "
                          "burn over its threshold at once to page "
                          "(multi-window AND; scaled-down analogue of "
                          "the SRE-workbook 1h/6h pair)."),
    "MXTPU_SLO_OBJECTIVE": ("0.99", "Good-fraction objective shared by "
                            "the built-in SLOs (0.99 = 1% error "
                            "budget)."),
    "MXTPU_SLO_SERVE_P99_MS": ("250", "Serve-latency SLO threshold: a "
                               "request slower than this is an "
                               "error-budget spend."),
    "MXTPU_SLO_STEP_MS": ("60000", "Train step-time SLO threshold (ms) "
                          "for the train-step-time objective."),
    "MXTPU_SLO_ITL_P50_MS": ("100", "Decode inter-token-latency SLO "
                             "threshold (ms) for the decode-itl-p50 "
                             "built-in objective."),
    "MXTPU_SLO_ITL_P99_MS": ("500", "Decode inter-token-latency SLO "
                             "threshold (ms) for the decode-itl-p99 "
                             "built-in objective."),
}


def getenv(name: str, default: Optional[str] = None) -> Optional[str]:
    if default is None and name in ENV_VARS:
        default = ENV_VARS[name][0]
    return os.environ.get(name, default)


def setenv(name: str, value: str) -> None:
    os.environ[name] = value


def env_var_doc() -> str:
    lines = ["| Variable | Default | Description |", "|---|---|---|"]
    for k, (d, desc) in sorted(ENV_VARS.items()):
        lines.append(f"| {k} | {d!r} | {desc} |")
    return "\n".join(lines)


def makedirs(d: str) -> None:
    os.makedirs(d, exist_ok=True)


# --- numpy-semantics switches (reference: mx.util.set_np / np_shape) -------
_NP_SHAPE = [True]   # TPU build: numpy semantics are the native behavior
_NP_ARRAY = [False]


def is_np_shape() -> bool:
    return _NP_SHAPE[0]


def is_np_array() -> bool:
    return _NP_ARRAY[0]


def set_np(shape: bool = True, array: bool = True) -> None:
    _NP_SHAPE[0] = shape
    _NP_ARRAY[0] = array


def reset_np() -> None:
    set_np(True, False)


class np_shape:
    """Context manager parity for ``mx.util.np_shape``."""

    def __init__(self, active: bool = True):
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = _NP_SHAPE[0]
        _NP_SHAPE[0] = self._active
        return self

    def __exit__(self, *exc):
        _NP_SHAPE[0] = self._prev


def use_np_shape(fn):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with np_shape(True):
            return fn(*args, **kwargs)
    return wrapped

from .optimizer import (  # noqa: F401
    Optimizer, SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, AdaDelta, FTRL,
    Signum, LAMB, LARS, Updater, register, create, get_updater,
)

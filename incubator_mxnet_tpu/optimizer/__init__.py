from .optimizer import (  # noqa: F401
    Optimizer, SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, AdaDelta, FTRL, Ftrl,
    Signum, LAMB, LARS, DCASGD, SGLD, Adamax, Nadam, FTML, Updater, register,
    create, get_updater,
)

"""Optimizers (reference: ``python/mxnet/optimizer/optimizer.py`` +
``src/operator/optimizer_op.cc``).

The reference implements each update rule as a mutating operator
(``FMutateInputs``) launched per-parameter. Here each rule is a jitted pure
function ``(weight, grad, *state, lr, wd, ...) -> (new_weight, *new_state)``;
the NDArray facade swaps buffers (mutation semantics preserved). XLA's
executable cache plays the role of the reference's per-op kernel cache, and
the Trainer's fused path (gluon/trainer.py) applies all parameters in one
compiled update — the multi-tensor optimizer fusion the reference ships as
``multi_sgd_update``/LAMB multi-tensor contrib ops.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..base import Registry, MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "RMSProp", "AdaGrad",
           "AdaDelta", "FTRL", "Ftrl", "Signum", "LAMB", "LARS", "DCASGD",
           "SGLD", "Adamax", "Nadam", "FTML", "Updater",
           "register", "create", "get_updater"]

_registry: Registry = Registry.get("optimizer")
register = _registry.register


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    return _registry.create(name, **kwargs)


class Optimizer:
    """Base optimizer. State is a tuple of jax arrays per parameter index."""

    def __init__(self, learning_rate=0.01, wd=0.0, rescale_grad=1.0,
                 clip_gradient=None, lr_scheduler=None, multi_precision=False,
                 param_dict=None, begin_num_update=0, **kwargs):
        self.lr = learning_rate
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.multi_precision = multi_precision
        self.param_dict = param_dict or {}
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name: Dict[int, str] = {}
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}

    # -- bookkeeping (reference parity) -----------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("learning rate is managed by the LRScheduler")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult: Dict[Any, float]):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[Any, float]):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        self._index_update_count.setdefault(index, self.begin_num_update)
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- per-param state ---------------------------------------------------
    def create_state(self, index, weight: NDArray) -> Tuple:
        return ()

    def create_state_multi_precision(self, index, weight: NDArray) -> Tuple:
        if self.multi_precision and weight.dtype in ("float16", "bfloat16"):
            master = weight._data.astype(jnp.float32)
            return (master,) + self.create_state(index, weight)
        return self.create_state(index, weight)

    # -- update ------------------------------------------------------------
    def _prep_grad(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def step(self, weight_v, grad_v, state, lr, wd, t):
        """Pure update rule; subclasses implement."""
        raise NotImplementedError

    def update(self, index, weight: NDArray, grad: NDArray, state) -> Any:
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        w, g = weight._data, grad._data
        use_master = (self.multi_precision and len(state) > 0
                      and isinstance(state, tuple) and getattr(state[0], "dtype", None) == jnp.float32
                      and w.dtype in (jnp.float16, jnp.bfloat16))
        if use_master:
            master, rest = state[0], state[1:]
            new_master, new_rest = self.step(master, g.astype(jnp.float32), rest, lr, wd, t)
            weight._set_data(new_master.astype(w.dtype))
            return (new_master,) + tuple(new_rest)
        new_w, new_state = self.step(w, g.astype(w.dtype) if g.dtype != w.dtype else g, state, lr, wd, t)
        weight._set_data(new_w)
        return tuple(new_state)

    update_multi_precision = update

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.lr})"


@register
class SGD(Optimizer):
    def __init__(self, momentum=0.0, lazy_update=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (jnp.zeros(weight.shape, weight._data.dtype),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        if self.momentum == 0.0:
            return w - lr * g, ()
        mom = state[0] * self.momentum - lr * g
        return w + mom, (mom,)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight._data.dtype),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        mom = self.momentum * state[0] + g
        return w - lr * (g + self.momentum * mom), (mom,)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        def z():
            return jnp.zeros(weight.shape, weight._data.dtype)
        return (z(), z())

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        m = self.beta1 * state[0] + (1 - self.beta1) * g
        v = self.beta2 * state[1] + (1 - self.beta2) * jnp.square(g)
        lr_t = lr * jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        return w - lr_t * m / (jnp.sqrt(v) + self.epsilon), (m, v)


@register
class AdamW(Adam):
    """Decoupled weight decay (reference: contrib ``adamw_update``)."""

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g)
        m = self.beta1 * state[0] + (1 - self.beta1) * g
        v = self.beta2 * state[1] + (1 - self.beta2) * jnp.square(g)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        return w - lr * (mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w), (m, v)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        def z():
            return jnp.zeros(weight.shape, weight._data.dtype)
        if self.centered:
            return (z(), z(), z())  # n, g_bar, delta
        return (z(),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        if not self.centered:
            n = self.rho * state[0] + (1 - self.rho) * jnp.square(g)
            neww = w - lr * g / jnp.sqrt(n + self.epsilon)
            return neww, (n,)
        n = self.rho * state[0] + (1 - self.rho) * jnp.square(g)
        gbar = self.rho * state[1] + (1 - self.rho) * g
        delta = self.momentum * state[2] - lr * g / jnp.sqrt(n - jnp.square(gbar) + self.epsilon)
        neww = w + delta
        if self.clip_weights:
            neww = jnp.clip(neww, -self.clip_weights, self.clip_weights)
        return neww, (n, gbar, delta)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight._data.dtype),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        hist = state[0] + jnp.square(g)
        return w - lr * g / (jnp.sqrt(hist) + self.float_stable_eps), (hist,)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        def z():
            return jnp.zeros(weight.shape, weight._data.dtype)
        return (z(), z())

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        acc_g = self.rho * state[0] + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(state[1] + self.epsilon) / jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * state[1] + (1 - self.rho) * jnp.square(delta)
        return w - delta, (acc_g, acc_d)


@register
class FTRL(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        def z():
            return jnp.zeros(weight.shape, weight._data.dtype)
        return (z(), z())  # z, n

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g)
        zs, n = state
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        zs = zs + g - sigma * w
        n = n + jnp.square(g)
        neww = jnp.where(
            jnp.abs(zs) > self.lamda1,
            -(zs - jnp.sign(zs) * self.lamda1) / ((self.beta + jnp.sqrt(n)) / lr + wd),
            0.0,
        )
        return neww.astype(w.dtype), (zs, n)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (jnp.zeros(weight.shape, weight._data.dtype),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g)
        if self.momentum == 0.0:
            return w * (1 - lr * self.wd_lh) - lr * jnp.sign(g + wd * w), ()
        mom = self.momentum * state[0] - (1 - self.momentum) * (g + wd * w)
        return w * (1 - lr * self.wd_lh) + lr * jnp.sign(mom), (mom,)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments (reference: contrib lamb_update_phase1/2),
    the BERT-large large-batch optimizer of the north star."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lower_bound=None, upper_bound=None, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        def z():
            return jnp.zeros(weight.shape, jnp.float32)
        return (z(), z())

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g).astype(jnp.float32)
        wf = w.astype(jnp.float32)
        m = self.beta1 * state[0] + (1 - self.beta1) * g
        v = self.beta2 * state[1] + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * wf
        w_norm = jnp.linalg.norm(wf)
        r_norm = jnp.linalg.norm(r)
        if self.lower_bound is not None:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound is not None:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (wf - lr * trust * r).astype(w.dtype), (m, v)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (ResNet large-batch recipes)."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight._data.dtype),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g)
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where((w_norm > 0) & (g_norm > 0),
                          self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        mom = self.momentum * state[0] + lr * trust * (g + wd * w)
        return w - mom, (mom,)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD —
    Zheng et al.): compensates gradient staleness with a λ·g²·(w − w_prev)
    term."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        # reference parity: no momentum buffer at the default momentum=0.0
        if self.momentum == 0.0:
            return (weight._data,)     # (previous weight,)
        z = jnp.zeros(weight.shape, weight._data.dtype)
        return (z, weight._data)       # (momentum, previous weight)

    def step(self, w, g, state, lr, wd, t):
        # Delay compensation uses the RAW (rescaled/clipped) gradient; weight
        # decay enters the update separately (reference: dcasgd_update's
        # lamda*grad*grad*(weight - previous_weight) + wd*weight).
        g = self._prep_grad(g)
        prev = state[-1]
        comp = g + wd * w + self.lamda * jnp.square(g) * (w - prev)
        if self.momentum == 0.0:
            return w - lr * comp, (w,)
        mom = self.momentum * state[0] - lr * comp
        return w + mom, (mom, w)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py SGLD):
    SGD plus N(0, lr) gradient noise — a Bayesian sampler, not a descent
    method. Each parameter's state carries its own base key drawn from the
    global RNG (so mx.random.seed governs it and parameters decorrelate);
    the step counter folds in per update for jit purity."""

    def create_state(self, index, weight):
        from .. import random as _rng
        self._key_impl = _rng._impl()
        base = jax.random.fold_in(_rng.next_key(), index)
        # store RAW key data (plain uint32) so optimizer states stay
        # picklable/serializable like every other state array
        return (jax.random.key_data(base),)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        base = jax.random.wrap_key_data(
            state[0], impl=getattr(self, "_key_impl", None) or "threefry2x32")
        key = jax.random.fold_in(base, t)
        noise = jax.random.normal(key, w.shape, jnp.float32) * jnp.sqrt(lr)
        return w - 0.5 * lr * g + noise.astype(w.dtype), state


@register
class Adamax(Optimizer):
    """Adam with an infinity-norm second moment (reference: Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight._data.dtype)
        return (z, z)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        m = self.beta1 * state[0] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * state[1], jnp.abs(g))
        lr_t = lr / (1.0 - self.beta1 ** t)
        return w - lr_t * m / (u + 1e-8), (m, u)


@register
class Nadam(Optimizer):
    """Adam with Nesterov momentum (reference: Nadam, Dozat 2016)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon, self.schedule_decay = epsilon, schedule_decay

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight._data.dtype)
        return (z, z, jnp.ones((), jnp.float32))   # (m, v, m_schedule)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        m_prev, v_prev, m_schedule = state
        mu_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_t1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        # cumulative momentum schedule (reference: m_schedule *= mu_t)
        m_schedule = m_schedule * mu_t
        m_schedule_next = m_schedule * mu_t1
        m = self.beta1 * m_prev + (1 - self.beta1) * g
        v = self.beta2 * v_prev + (1 - self.beta2) * jnp.square(g)
        g_hat = g / (1 - m_schedule)
        m_hat = m / (1 - m_schedule_next)
        m_bar = (1 - mu_t) * g_hat + mu_t1 * m_hat
        v_hat = v / (1 - self.beta2 ** t)
        return (w - lr * m_bar / (jnp.sqrt(v_hat) + self.epsilon),
                (m, v, m_schedule))


@register
class FTML(Optimizer):
    """Follow the moving leader (reference: FTML, Zheng & Kwok 2017)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight._data.dtype)
        return (z, z, z)               # (v, d, z)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep_grad(g) + wd * w
        v_prev, d_prev, z_prev = state
        v_t = self.beta2 * v_prev + (1 - self.beta2) * jnp.square(g)
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v_t / (1 - self.beta2 ** t)) + self.epsilon)
        sigma_t = d_t - self.beta1 * d_prev
        z_t = self.beta1 * z_prev + (1 - self.beta1) * g - sigma_t * w
        return -z_t / d_t, (v_t, d_t, z_t)


Ftrl = FTRL  # reference exposes both spellings


class Updater:
    """Stateful (index, weight, grad) applier — reference ``get_updater``
    surface used by KVStore server-side optimization."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[int, Any] = {}

    def __call__(self, index, grad: NDArray, weight: NDArray):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        self.states[index] = self.optimizer.update(index, weight, grad, self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle

        host = {k: jax.tree_util.tree_map(lambda a: __import__("numpy").asarray(a), v)
                for k, v in self.states.items()}
        return pickle.dumps((host, self.optimizer if dump_optimizer else None))

    def set_states(self, states: bytes):
        import pickle

        host, opt = pickle.loads(states)
        self.states = {k: jax.tree_util.tree_map(jnp.asarray, v) for k, v in host.items()}
        if opt is not None:
            self.optimizer = opt


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)

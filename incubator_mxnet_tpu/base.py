"""Base utilities: dtype handling, env-var config plane, registries.

TPU-native counterpart of the reference's dmlc-core roles
(``3rdparty/dmlc-core/include/dmlc/``: ``dmlc::GetEnv``, ``dmlc::Registry``,
``dmlc::Parameter``) and ``include/mxnet/base.h``. See SURVEY.md §2.1/§5.6.

Design: no C ABI is needed between Python and the device runtime — JAX/PjRt is
the runtime boundary. The registry here plays the role of the reference's
``dmlc::Registry`` / NNVM op registry for Python-visible components
(optimizers, initializers, kvstores, losses, data iterators, metrics).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Generic, List, Optional, Type, TypeVar

import numpy as onp

__all__ = [
    "MXNetError",
    "get_env",
    "env_bool",
    "env_int",
    "Registry",
    "string_types",
    "numeric_types",
    "integer_types",
    "_as_list",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (name kept for reference parity:
    ``python/mxnet/base.py (MXNetError)``)."""


string_types = (str,)
numeric_types = (float, int, onp.generic)
integer_types = (int, onp.integer)


def _as_list(obj) -> list:
    """Normalize an object to a list (reference: ``python/mxnet/base.py``)."""
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


# ---------------------------------------------------------------------------
# Env-var config plane (reference: dmlc::GetEnv; catalog in docs/ENV_VARS.md)
# ---------------------------------------------------------------------------

def get_env(name: str, default: Any = None, typ: Optional[type] = None) -> Any:
    """Read a config env var (``MXNET_*`` namespace kept for familiarity)."""
    val = os.environ.get(name)
    if val is None:
        return default
    if typ is None and default is not None:
        typ = type(default)
    if typ is bool:
        return val.lower() in ("1", "true", "yes", "on")
    if typ is not None:
        return typ(val)
    return val


def env_bool(name: str, default: bool = False) -> bool:
    return get_env(name, default, bool)


def env_int(name: str, default: int = 0) -> int:
    return get_env(name, default, int)


# ---------------------------------------------------------------------------
# Registry (reference: dmlc::Registry / python/mxnet/registry.py)
# ---------------------------------------------------------------------------

T = TypeVar("T")


class Registry(Generic[T]):
    """A named registry of classes/functions with alias support."""

    _registries: Dict[str, "Registry"] = {}

    def __init__(self, name: str):
        self.name = name
        self._entries: Dict[str, T] = {}
        Registry._registries[name] = self

    @staticmethod
    def get(name: str) -> "Registry":
        if name not in Registry._registries:
            Registry(name)
        return Registry._registries[name]

    def register(self, entry: Optional[T] = None, name: Optional[str] = None):
        def _do(e: T) -> T:
            key = (name or getattr(e, "__name__", str(e))).lower()
            self._entries[key] = e
            return e

        if entry is None:
            return _do
        return _do(entry)

    def alias(self, existing: str, *aliases: str) -> None:
        for a in aliases:
            self._entries[a.lower()] = self._entries[existing.lower()]

    def find(self, name: str) -> Optional[T]:
        return self._entries.get(name.lower())

    def create(self, name: str, *args, **kwargs):
        entry = self.find(name)
        if entry is None:
            raise MXNetError(
                f"{self.name} registry has no entry '{name}'. "
                f"Known: {sorted(self._entries)}"
            )
        return entry(*args, **kwargs)

    def list(self) -> List[str]:
        return sorted(self._entries)

"""Training callbacks (reference: python/mxnet/callback.py — SURVEY §5.5).

``Speedometer`` reports samples/sec; ``do_checkpoint`` saves per epoch;
``LogValidationMetricsCallback`` logs eval metrics. Signature-compatible with
Module.fit's epoch/batch callback slots.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "LogValidationMetricsCallback", "module_checkpoint"]


class Speedometer:
    """Log throughput every ``frequent`` batches (reference parity)."""

    def __init__(self, batch_size: int, frequent: int = 50,
                 auto_reset: bool = True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param) -> None:
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s" % (
                        param.epoch, count, speed,
                        "\t".join(f"{n}={v:.6f}" for n, v in name_value))
                else:
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" % (
                        param.epoch, count, speed)
                logging.info(msg)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


def do_checkpoint(prefix: str, period: int = 1):
    """Epoch-end callback saving symbol+params (reference: do_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            from . import model
            model.save_checkpoint(prefix, iter_no + 1, sym, arg or {}, aux or {})
    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period: int, auto_reset: bool = False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class LogValidationMetricsCallback:
    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)

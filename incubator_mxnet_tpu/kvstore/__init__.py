"""KVStore — the gradient-exchange / parameter-synchronization API.

Reference parity (SURVEY §2.5, §5.8): ``include/mxnet/kvstore.h``
(``KVStore::Create/Push/Pull``), with three in-tree backends — local device
comm (``src/kvstore/comm.h``), NCCL all-reduce (``src/kvstore/kvstore_nccl.h``)
and the ps-lite parameter server (``src/kvstore/kvstore_dist.h``) — plus the
``KVStoreBase`` plugin registry that Horovod/BytePS attach through.

TPU-native design: ONE execution mechanism — XLA collectives over the
ICI/DCN mesh — behind the same API names:

========================  =================================================
``create('local')``       in-process aggregating store (CommCPU parity)
``create('device')``      same (device memory IS the store; XLA manages it)
``create('nccl')``        mesh all-reduce on push (KVStoreNCCL parity)
``create('dist_sync')``   same compiled psum, spanning hosts after
                          ``parallel.dist.initialize()`` (ps-lite's
                          scheduler role). Synchronous by construction.
``create('dist_async')``  a REAL async parameter server (``async_ps.py``):
                          TCP PS thread on rank 0, pushes applied in
                          arrival order with no barrier — ps-lite's role,
                          host-side beside the XLA path exactly as the
                          reference's ps-lite sits beside its kernels.
========================  =================================================

``set_optimizer`` enables update-on-kvstore exactly like the reference's
server-side optimizer (``KVStoreDistServer::DataHandleEx`` sync branch).
"""
from __future__ import annotations

import pickle
from typing import Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError
from ..ndarray import NDArray
from ..context import current_context

__all__ = ["KVStore", "KVStoreBase", "create", "kv_fallback_active"]

P = PartitionSpec


def kv_fallback_active() -> bool:
    """True when ``MXTPU_KVSTORE_FALLBACK=1`` opts into the per-parameter
    Python push/pull loop (the async-PS scenario, retry/exactly-once
    semantics per key). Default off: gradient exchange runs as ONE
    compiled collective per key batch — inside the pjit step for
    ``parallel.ShardedTrainer``, via the batched store push/pull for
    ``gluon.Trainer``."""
    from ..util import getenv
    return getenv("MXTPU_KVSTORE_FALLBACK", "0") == "1"


# ---------------------------------------------------------------------------
# The ONE execution mechanism: a jitted shard_map psum over a device mesh,
# batched over keys (KVStoreNCCL's grouped ncclAllReduce; SURVEY §2.5/§5.8).
# Executables are cached per (mesh devices, shapes/dtypes) — the analog of
# NCCL communicator reuse across pushes.
# ---------------------------------------------------------------------------

_AR_CACHE: Dict[tuple, Callable] = {}


def _select_rows(w: jax.Array, ids) -> jax.Array:
    """Dense row-select: zeros everywhere except rows named by ``ids``, which
    carry ``w``'s values — the dense-facade reading of a row_sparse pull.
    Shared by KVStore.row_sparse_pull and Trainer._row_sparse_pull."""
    idx = (ids._data if isinstance(ids, NDArray)
           else jnp.asarray(ids)).astype(jnp.int32).reshape(-1)
    return jnp.zeros_like(w).at[idx].set(w[idx])


def _allreduce_fn(mesh: Mesh, sig: tuple) -> Callable:
    """Compiled all-reduce over the leading (device) axis for a tuple of
    stacked arrays — ONE executable for the whole key batch; XLA emits one
    fused all-reduce (verified in tests via the lowered HLO)."""
    key = (tuple(mesh.devices.flat), sig)
    fn = _AR_CACHE.get(key)
    if fn is None:
        from ..parallel.collectives import shard_map

        def reduce_all(*xs):
            return tuple(jax.lax.psum(x, "kv") for x in xs)

        fn = jax.jit(shard_map(
            reduce_all, mesh=mesh,
            in_specs=tuple(P("kv") for _ in sig),
            out_specs=tuple(P("kv") for _ in sig)))
        _AR_CACHE[key] = fn
    return fn


def _device_allreduce(batches: List[List[jax.Array]]) -> List[jax.Array]:
    """Sum each key's replica list with one compiled cross-device collective.

    ``batches``: per key, the list of replica arrays (all same shape).
    Co-located replicas are pre-summed with device-local adds; the distinct
    devices then join ONE jitted psum, their replicas stacked zero-copy into
    an array sharded over a 1-D mesh. When multi-process, the mesh spans
    every process's devices (devices without a replica contribute zeros), so
    the same single executable is the pod-wide all-reduce — psum over
    ICI/DCN exactly where the reference's ncclAllReduce sat. Returns, per
    key, a mesh-sharded array in which EVERY contributing device holds the
    full sum as its shard (read back per device without further transfers).
    """
    per_key = []
    for vlist in batches:
        by_dev: Dict = {}
        for v in vlist:
            d = next(iter(v.devices()))
            by_dev[d] = v if d not in by_dev else by_dev[d] + v
        per_key.append(by_dev)
    multi = jax.process_count() > 1
    used = sorted({d for bk in per_key for d in bk}, key=lambda d: d.id)
    if not multi and len(used) == 1:
        return [next(iter(bk.values())) for bk in per_key]
    devices = list(jax.devices()) if multi else used
    local_devices = jax.local_devices() if multi else used
    mesh = Mesh(onp.array(devices), ("kv",))
    n_dev = len(devices)
    stacked, sig = [], []
    for by_dev in per_key:
        sample = next(iter(by_dev.values()))
        shape, dtype = tuple(sample.shape), sample.dtype
        shards = []
        for d in local_devices:
            src = by_dev.get(d)
            buf = (jax.device_put(jnp.zeros(shape, dtype), d)
                   if src is None else src)
            shards.append(buf.reshape((1,) + shape))
        arr = jax.make_array_from_single_device_arrays(
            (n_dev,) + shape, NamedSharding(mesh, P("kv")), shards)
        stacked.append(arr)
        sig.append((shape, str(dtype)))
    outs = _allreduce_fn(mesh, tuple(sig))(*stacked)
    return list(outs)


_REGISTRY: Dict[str, type] = {}


def register(name_or_cls=None):
    """Backend plugin registry (reference: KVStoreBase plugin seam,
    python/mxnet/kvstore/base.py). Usable as ``@register`` or
    ``@register("name")``."""
    def _do(cls, name=None):
        _REGISTRY[(name or cls.__name__).lower()] = cls
        return cls
    if isinstance(name_or_cls, str):
        return lambda cls: _do(cls, name_or_cls)
    if name_or_cls is not None:
        return _do(name_or_cls)
    return _do


def create(name: str = "local", **kwargs) -> "KVStoreBase":
    """KVStore factory (reference: ``mx.kv.create`` → ``KVStore::Create``)."""
    if not isinstance(name, str):
        raise MXNetError(f"KVStore name must be a string, got {type(name)}")
    key = name.lower()
    if key in ("dist_async",):
        from .async_ps import AsyncKVStore
        return AsyncKVStore(**kwargs)
    if key in ("local", "device", "local_allreduce_cpu", "local_allreduce_device"):
        return KVStore(comm="local", **kwargs)
    if key in ("nccl", "mesh", "dist", "dist_sync", "dist_device_sync",
               "horovod", "byteps"):
        return KVStore(comm="mesh", **kwargs)
    if key in _REGISTRY:
        return _REGISTRY[key](**kwargs)
    raise MXNetError(
        f"Unknown KVStore type '{name}'. Built-ins: local, device, nccl, "
        f"dist_sync, dist_async; plugins: {sorted(_REGISTRY)}")


@jax.jit
def _twobit_step(g, res, threshold):
    """One error-feedback quantization step (shared executable across
    pushes/keys of the same shape)."""
    acc = g + res
    q = jnp.where(acc >= threshold, threshold,
                  jnp.where(acc <= -threshold, -threshold, 0.0)
                  ).astype(g.dtype)
    return q, acc - q


class GradientCompressionMixin:
    """2-bit gradient compression with error feedback (reference:
    src/kvstore/gradient_compression.cc TwoBitCompressor) — shared by the
    sync store and the async PS so validation/semantics can't diverge.
    Hosts must initialize ``self._compression = {}`` / ``self._residuals =
    {}`` and call ``self._compress(key, replica_idx, grad)`` per replica
    before aggregation."""

    def set_gradient_compression(self, compression_params: dict):
        """Each replica's push is quantized per key to {-threshold, 0,
        +threshold} BEFORE aggregation, with the quantization residual
        carried into the next push (error feedback) — the reference's
        numerical semantics exactly. Note the wire still moves full-width
        floats (values are ternary but not bit-packed), so this provides
        the reference's *convergence semantics*, not byte savings."""
        params = dict(compression_params or {})
        ctype = params.get("type", params.get("compression"))
        if not params or ctype in ("none",):
            self._compression = {}
            self._residuals = {}
            return
        if ctype is None:
            raise MXNetError("gradient compression params need a 'type' "
                             "key (supported: '2bit')")
        if ctype != "2bit":
            raise MXNetError(f"unsupported gradient compression {ctype!r}; "
                             "supported: '2bit'")
        self._compression = params
        self._residuals = {}

    def _compress(self, k, rep_idx, g: jnp.ndarray) -> jnp.ndarray:
        """Quantize one replica's gradient for key ``k`` (error feedback
        state per (key, replica) — reference: per-worker residual arrays)."""
        if not self._compression:
            return g
        threshold = jnp.asarray(
            float(self._compression.get("threshold", 0.5)), g.dtype)
        rkey = (k, rep_idx)
        res = self._residuals.get(rkey)
        if res is None or res.shape != g.shape:
            res = jnp.zeros_like(g)
        q, new_res = _twobit_step(g, res, threshold)
        self._residuals[rkey] = new_res
        return q


class KVStoreBase:
    """Minimal backend interface (reference: kvstore/base.py)."""

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority: int = 0):
        raise NotImplementedError

    def pull(self, key, out=None, priority: int = 0, ignore_sparse: bool = True):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority: int = 0):
        self.push(key, value, priority)
        return self.pull(key, out=out, priority=priority)

    def broadcast(self, key, value, out=None, priority: int = 0):
        self.init(key, value)
        return self.pull(key, out=out, priority=priority)

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1


class KVStore(GradientCompressionMixin, KVStoreBase):
    """The aggregating store.

    Semantics follow the reference local kvstore: ``init`` seeds a key;
    ``push`` *accumulates* (a list value pushes the sum of the list — the
    multi-device gradient reduce of ``CommDevice``); ``pull`` returns the
    merged value (after the optimizer update when one is set).

    comm='mesh' additionally sums pushes across *processes* with a compiled
    ``psum`` over all devices (KVStoreNCCL / dist_sync parity). Single
    process on one device it degenerates to local — same code path the
    reference gets with one GPU.
    """

    def __init__(self, comm: str = "local"):
        self._comm = comm
        self._store: Dict[Union[int, str], NDArray] = {}
        self._merged: Dict[Union[int, str], NDArray] = {}
        #: per key, {device: full-sum shard} left behind by the collective —
        #: lets pull() hand every replica its device-resident copy for free
        self._merged_shards: Dict[Union[int, str], Dict] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None
        self._opt_states: Dict[Union[int, str], tuple] = {}
        self._compression: Dict[str, float] = {}
        self._residuals: Dict = {}

    # -- identity ----------------------------------------------------------
    @property
    def type(self) -> str:
        return "device" if self._comm == "local" else "dist_sync"

    @property
    def rank(self) -> int:
        return jax.process_index() if self._comm == "mesh" else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self._comm == "mesh" else 1

    # -- core ops ----------------------------------------------------------
    def _keys(self, key):
        return key if isinstance(key, (list, tuple)) else [key]

    def _vals(self, key, value):
        if isinstance(key, (list, tuple)):
            if len(key) != len(value):
                raise MXNetError("key list and value list length mismatch")
            return list(value)
        return [value]

    def init(self, key, value):
        for k, v in zip(self._keys(key), self._vals(key, value)):
            if isinstance(v, (list, tuple)):
                v = v[0]
            if k in self._store:
                continue
            self._store[k] = NDArray(jnp.array(v._data))

    def push(self, key, value, priority: int = 0):
        """Accumulate. comm='mesh' sums every key's replica list — and, when
        multi-process, every process's push — in ONE compiled collective per
        key batch (``_device_allreduce``; KVStoreNCCL / dist_sync parity).
        Push a key *list* to get the reference's grouped-all-reduce batching.
        """
        items = []
        for k, v in zip(self._keys(key), self._vals(key, value)):
            vlist = v if isinstance(v, (list, tuple)) else [v]
            items.append((k, [self._compress(k, i, x._data)
                              for i, x in enumerate(vlist)]))
        if self._comm == "mesh":
            sums = _device_allreduce([b for _, b in items])
            merged_list = []
            for (k, _), s in zip(items, sums):
                if len(s.devices()) > 1:  # mesh-sharded full-sum result
                    shards = {sh.device: sh.data.reshape(s.shape[1:])
                              for sh in s.addressable_shards}
                    self._merged_shards[k] = shards
                    merged_list.append((k, next(iter(shards.values()))))
                else:
                    self._merged_shards.pop(k, None)
                    merged_list.append((k, s))
        else:
            merged_list = []
            for k, b in items:
                total = b[0]
                for a in b[1:]:
                    total = total + a.astype(total.dtype)
                merged_list.append((k, total))
        for k, merged in merged_list:
            if self._updater is not None or self._optimizer is not None:
                if k not in self._store:
                    raise MXNetError(f"please init key {k!r} before push")
                # pull() must see the UPDATED WEIGHT, not the gradient sum
                # the collective left per device.
                self._merged_shards.pop(k, None)
                self._apply_update(k, merged)
            else:
                self._merged[k] = NDArray(merged)

    def pull(self, key, out=None, priority: int = 0, ignore_sparse: bool = True):
        results = []
        for k in self._keys(key):
            if self._updater is not None or self._optimizer is not None:
                src = self._store.get(k)
            else:
                src = self._merged.get(k, self._store.get(k))
            if src is None:
                raise MXNetError(f"key {k!r} was never initialized or pushed")
            results.append(src)
        if out is not None:
            if isinstance(key, (list, tuple)):
                # per-key out slot; each slot may be a replica list
                outs = out
                if len(outs) != len(results):
                    raise MXNetError("pull: out list length != key list length")
            else:
                outs = [out]
            for k, o, r in zip(self._keys(key), outs, results):
                shards = self._merged_shards.get(k, {})
                for oo in (o if isinstance(o, (list, tuple)) else [o]):
                    # Zero transfer when the collective already left the full
                    # sum on this replica's device.
                    dev = next(iter(oo._data.devices()), None)
                    src = shards.get(dev, r._data)
                    oo._set_data(src.astype(oo.dtype))
            return out
        return results if isinstance(key, (list, tuple)) else results[0]

    def row_sparse_pull(self, key, out=None, priority: int = 0, row_ids=None):
        """Pull only the rows named by ``row_ids`` (reference:
        KVStore.row_sparse_pull over row_sparse values). Storage here is the
        dense facade (SURVEY §7 sparse scoping), so the result is a dense
        array with the requested rows populated and every other row zero —
        the same values a reference caller reads out of the returned
        row_sparse array, without the index bookkeeping."""
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        keys = self._keys(key)
        if out is None:
            raise MXNetError("row_sparse_pull needs out= when row_ids given")
        if isinstance(key, (list, tuple)):
            # multi-key: out / row_ids are per-key lists
            outs = list(out)
            ids_list = list(row_ids) if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(keys)
        else:
            # single key: the reference pairs row_ids with OUT slots —
            # kv.row_sparse_pull('emb', out=[o1, o2], row_ids=[r1, r2])
            # fills each out with its own row set
            keys = keys * (len(out) if isinstance(out, (list, tuple)) else 1)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            ids_list = list(row_ids) if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(outs)
        if not len(keys) == len(outs) == len(ids_list):
            raise MXNetError(
                f"row_sparse_pull: mismatched lengths — {len(keys)} keys, "
                f"{len(outs)} outs, {len(ids_list)} row_ids")
        for k, o, ids in zip(keys, outs, ids_list):
            src = self._store.get(k)
            if src is None:
                raise MXNetError(f"key {k!r} was never initialized")
            rows = _select_rows(src._data, ids)
            for oo in (o if isinstance(o, (list, tuple)) else [o]):
                oo._set_data(rows.astype(oo.dtype))
        return out

    # -- server-side optimizer (update_on_kvstore) -------------------------
    def set_updater(self, updater: Callable):
        """reference: KVStore.set_updater / server controller fn."""
        self._updater = updater

    def set_optimizer(self, optimizer):
        from .. import optimizer as opt_mod
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self._optimizer = optimizer

    def _apply_update(self, k, grad):
        weight = self._store[k]
        if self._updater is not None:
            self._updater(k, NDArray(grad), weight)
            return
        idx = k if isinstance(k, int) else abs(hash(k)) % (2 ** 31)
        if k not in self._opt_states:
            self._opt_states[k] = self._optimizer.create_state_multi_precision(
                idx, weight)
        self._opt_states[k] = self._optimizer.update(
            idx, weight, NDArray(grad), self._opt_states[k])

    # -- persistence (reference: MXKVStoreSaveOptimizerStates) -------------
    def save_optimizer_states(self, fname: str, dump_optimizer: bool = False):
        # host-0 election (MX902): comm='mesh' replicates the optimizer
        # states across processes, so every host holds the same blob and
        # exactly one may write it — single-process stores are always
        # primary, so the local path is unchanged
        from ..parallel.dist import is_primary
        if not is_primary():
            return
        blob = {"states": {k: tuple(onp.asarray(s._data if isinstance(s, NDArray)
                                                else s) for s in st)
                           for k, st in self._opt_states.items()}}
        with open(fname, "wb") as f:
            pickle.dump(blob, f)

    def load_optimizer_states(self, fname: str):
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._opt_states = {k: tuple(jnp.asarray(s) for s in st)
                            for k, st in blob["states"].items()}

    def barrier(self):
        """Global barrier (reference: kvstore barrier via ps-lite)."""
        if self._comm == "mesh" and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def __repr__(self):
        return f"KVStore(type={self.type!r}, keys={len(self._store)})"

"""Asynchronous parameter server — kvstore type ``dist_async``.

Reference counterpart: ``src/kvstore/kvstore_dist_server.h``
(``DataHandleEx`` async branch over ps-lite): each worker's push is handled
IMMEDIATELY in arrival order — no cross-worker barrier — and pull returns
whatever the server holds right now. Gradient staleness is traded for
throughput; convergence analysis is the user's problem (same contract as
the reference).

TPU-native position: the COMPILED training path stays on XLA collectives
(``dist_sync``) — every XLA collective is a synchronization point by
construction, so async semantics cannot ride one. Exactly like the
reference, whose ps-lite is host-side networking beside the device kernels,
the async store is host-side networking beside the XLA step: a TCP
parameter server thread on rank 0, length-prefixed pickled messages, pushes
handled under a store lock in arrival order. ps-lite's scheduler/van roles
collapse to one listening socket because the worker set is fixed at launch
(DMLC_* env, SURVEY §2.5).

Semantics, mirroring :class:`~incubator_mxnet_tpu.kvstore.KVStore`:

- no server optimizer: ``push`` REPLACES the key's merged value (each push
  is its own merge, as in the sync store); concurrent workers interleave
  latest-wins — the async staleness contract. ``pull`` reads the latest
  push (or the init value). This is what ``gluon.Trainer``'s
  push-grad/pull-merged step consumes.
- with ``set_optimizer`` (shipped pickled, the reference's server-side
  ``DataHandleEx`` update): every push updates the WEIGHTS immediately and
  ``pull`` returns them — update-on-kvstore, per-arrival.

Fault tolerance (``mx.fault`` wiring — the reference client died on the
first socket error):

- the client survives connection loss: every call runs under an
  env-tunable :class:`~incubator_mxnet_tpu.fault.retry.RetryPolicy`
  (``MXNET_KVSTORE_RETRIES`` / ``MXNET_KVSTORE_RETRY_DELAY``) that
  reconnects with exponential backoff and resends; the per-op socket
  timeout comes from ``MXNET_KVSTORE_TIMEOUT`` (default 60s). Exhaustion
  raises :class:`MXNetError` carrying the op + key, never a bare
  ``ConnectionError``.
- resends are safe because pushes are *versioned*: each client stamps a
  monotonically increasing version per push and the server remembers the
  last version applied per (worker, key) — a retry of a push whose first
  copy DID land (the reply was what got lost) is acknowledged without
  re-applying, so server-side optimizer updates are exactly-once.
- the server shuts down gracefully (``stop(checkpoint=...)``) and a new
  one restarts from that checkpoint on the same port
  (``AsyncPSServer(restore=...)``) — weights, merged buffers, optimizer
  state, and the applied-version table all survive.
- chaos hooks (``fault.inject``): ``kv_drop`` severs the client socket
  before a call, ``kv_delay`` stalls it — the seeded harness drives the
  full reconnect path in tests.
"""
from __future__ import annotations

import itertools
import os
import pickle
import socket
import struct
import threading
import time
from typing import Dict, Optional

import numpy as onp

from ..base import MXNetError
from ..fault import inject as _inject
from ..fault.retry import RetryExhausted, RetryPolicy
from ..lockcheck import make_lock
from ..ndarray import NDArray
from ..telemetry import events as _tele
from ..telemetry import metrics as _tmetrics
from ..telemetry import trace as _trace
from . import GradientCompressionMixin, KVStoreBase

__all__ = ["AsyncPSServer", "AsyncKVStore"]

_LEN = struct.Struct("<Q")


def _io_timeout() -> float:
    """Per-socket-op timeout (seconds) — MXNET_KVSTORE_TIMEOUT, default 60.
    Read per connection so tests/jobs can retune without reimporting."""
    try:
        return float(os.environ.get("MXNET_KVSTORE_TIMEOUT", "60"))
    except ValueError as e:
        raise MXNetError(f"bad MXNET_KVSTORE_TIMEOUT: {e}") from e


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class AsyncPSServer:
    """The rank-0 server: weights, latest-merged buffers, and an optional
    server-side optimizer applied per push in arrival order (DataHandleEx
    async semantics). One handler thread per worker connection; a single
    store lock serializes updates — the ordering guarantee the reference
    gets from ps-lite's per-key server queue."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 restore: Optional[str] = None):
        self._store: Dict = {}     # init values / optimizer-updated weights
        self._merged: Dict = {}    # latest pushed merge per key (no-opt mode)
        self._opt_states: Dict = {}
        self._optimizer = None
        self._lock = make_lock("AsyncPSServer._lock")
        self._push_count = 0
        #: (worker id, key) -> last applied push version: the resend-dedupe
        #: table that makes client retries exactly-once
        self._applied: Dict = {}
        if restore is not None:
            self._restore(restore)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns: set = set()       # live worker connections (for stop)
        self._thread = threading.Thread(target=self._serve,
                                        name="mx-kvstore-ps-accept",
                                        daemon=True)
        # attribute the server's parameter table on the device-memory
        # ledger (host-side numpy here, but it is the same weights a
        # device store pins — the "kvstore" site of telemetry.memory)
        from ..telemetry import memory as _tele_memory
        self._mem_unregister = _tele_memory.register_site(
            "kvstore", self._resident_bytes)
        self._thread.start()

    def _resident_bytes(self) -> int:
        with self._lock:
            return sum(int(getattr(v, "nbytes", 0) or 0)
                       for table in (self._store, self._merged)
                       for v in table.values())

    # -- message handling ---------------------------------------------------
    def _serve(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with self._lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._handle, args=(conn,),
                name=f"mx-kvstore-ps-handler-{conn.fileno()}",
                daemon=True)
            t.start()
        self._sock.close()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recv_msg(conn)
                stop = False
                try:
                    resp = self._dispatch(msg)
                    stop = msg[0] == "stop"
                except Exception as e:  # reply, keep the connection alive
                    resp = ("err", f"{type(e).__name__}: {e}")
                _send_msg(conn, resp)
                if stop:
                    self._stop.set()
                    return
        except (ConnectionError, EOFError, OSError):
            return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg):
        # a trailing {"_meta": 1, ...} dict is the carried trace context
        # (see _Client.call): pop it, resume the worker's trace, and span
        # the server-side handling — the client→PS hop becomes one
        # stitched edge instead of a correlation cliff, and a slow or
        # deduped resend is attributable to the training step that
        # issued the push
        if isinstance(msg[-1], dict) and msg[-1].get("_meta"):
            meta, msg = msg[-1], msg[:-1]
            key = msg[1] if len(msg) > 1 and not isinstance(
                msg[1], (bytes, bytearray)) else None
            step = meta.get("step")
            with _trace.use(_trace.from_wire(meta.get("trace"))), \
                    _trace.span(f"kvstore.server.{msg[0]}", kind="server",
                                key=key, step=step):
                if step is None:
                    return self._dispatch_inner(msg)
                # the carried step binds server-side events (resend,
                # errors) to the issuing step, same as the span above
                with _tele.step_scope(step):
                    return self._dispatch_inner(msg)
        return self._dispatch_inner(msg)

    def _dispatch_inner(self, msg):
        op = msg[0]
        if op == "init":
            _, key, arr = msg
            with self._lock:
                self._store.setdefault(key, onp.array(arr))
            return ("ok",)
        if op == "push":
            # ("push", key, arr) legacy or ("push", key, arr, wid, version)
            key, arr = msg[1], msg[2]
            wid, ver = (msg[3], msg[4]) if len(msg) >= 5 else (None, None)
            deduped = False
            with self._lock:
                if wid is not None:
                    if self._applied.get((wid, key), 0) >= ver:
                        deduped = True
                    else:
                        self._applied[(wid, key)] = ver
                if not deduped:
                    self._apply(key, onp.asarray(arr))
                    self._push_count += 1
            if deduped:
                # resend of an applied push: ack only — and say so on
                # the timeline (trace-correlated when the push carried
                # context), because an exactly-once dedupe firing is
                # the visible tail of a lost reply or a slow link.
                # Emitted OUTSIDE self._lock: subscriber fan-out can do
                # file I/O (the JSONL sink) and must not serialize every
                # concurrent push/pull behind it
                _tele.emit("kvstore.resend", key=key, worker=wid,
                           version=ver)
            return ("ok",)
        if op == "pull":
            _, key = msg
            with self._lock:
                if self._optimizer is not None:
                    val = self._store.get(key)
                else:
                    val = self._merged.get(key, self._store.get(key))
            if val is None:
                return ("err", f"key {key!r} not initialized")
            return ("ok", val)
        if op == "set_optimizer":
            _, blob = msg
            with self._lock:
                self._optimizer = pickle.loads(blob)
                self._opt_states.clear()
            return ("ok",)
        if op == "stats":
            with self._lock:
                return ("ok", {"pushes": self._push_count,
                               "keys": len(self._store)})
        if op == "stop":
            return ("ok",)
        return ("err", f"unknown op {op!r}")

    def _apply(self, key, grad: onp.ndarray) -> None:
        """Arrival-order push handling (lock held)."""
        if key not in self._store:
            raise MXNetError(f"push before init for key {key!r}")
        if self._optimizer is None:
            self._merged[key] = grad  # per-push merge; latest wins
            return
        w = NDArray(self._store[key])
        g = NDArray(grad)
        idx = key if isinstance(key, int) else abs(hash(key)) % (2 ** 31)
        state = self._opt_states.get(key)
        if state is None:
            state = self._optimizer.create_state(idx, w)
        self._opt_states[key] = self._optimizer.update(idx, w, g, state)
        self._store[key] = w.asnumpy()

    # -- graceful shutdown / restart ----------------------------------------
    def state_dict(self) -> dict:
        """Host-side snapshot of everything a restarted server needs."""
        import jax
        with self._lock:
            return {
                "format": 1,
                "store": {k: onp.asarray(v) for k, v in self._store.items()},
                "merged": {k: onp.asarray(v)
                           for k, v in self._merged.items()},
                "opt_states": {k: jax.tree.map(onp.asarray, st)
                               for k, st in self._opt_states.items()},
                "optimizer": (pickle.dumps(self._optimizer)
                              if self._optimizer is not None else None),
                "push_count": self._push_count,
                "applied": dict(self._applied),
            }

    def save_checkpoint(self, path: str) -> None:
        """Atomically persist :meth:`state_dict` (temp + ``os.replace``)."""
        blob = pickle.dumps(self.state_dict(),
                            protocol=pickle.HIGHEST_PROTOCOL)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            # intentional single-writer divergence: exactly one process
            # (rank 0) hosts the AsyncPSServer, so this save never races
            # a peer — the election happened at server construction
            with open(tmp, "wb") as f:  # mxlint: disable=MX902
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # mxlint: disable=MX902
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _restore(self, path: str) -> None:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if blob.get("format") != 1:
            raise MXNetError(f"{path}: unknown PS checkpoint format "
                             f"{blob.get('format')!r}")
        self._store = dict(blob["store"])
        self._merged = dict(blob["merged"])
        self._opt_states = dict(blob["opt_states"])
        self._optimizer = (pickle.loads(blob["optimizer"])
                           if blob["optimizer"] is not None else None)
        self._push_count = int(blob["push_count"])
        self._applied = dict(blob["applied"])

    def stop(self, checkpoint: Optional[str] = None) -> None:
        """Graceful shutdown: optionally checkpoint the store first, then
        stop accepting and join the accept loop (in-flight handler threads
        finish their current reply; they are daemons)."""
        if checkpoint is not None:
            self.save_checkpoint(checkpoint)
        self._stop.set()
        self._thread.join(timeout=2)
        # Close live worker connections so clients observe the shutdown and
        # fail over (retry/backoff) to a restarted server instead of
        # talking to this one's zombie handler threads.
        with self._lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class _Client:
    """Reconnecting PS client. Every call retries under the env retry
    policy; a lost connection is re-established with exponential backoff
    before the resend (safe for every op — pushes are versioned, the rest
    are idempotent reads/replaces)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retry: Optional[RetryPolicy] = None):
        self._host, self._port = host, port
        self._retry = retry or RetryPolicy.from_env()
        self._sock: Optional[socket.socket] = None
        self._ver = itertools.count(1)
        # registry handles resolved ONCE — per-op resolution would take
        # the registry lock on every push/pull of every tensor
        self._m = {
            "push": _tmetrics.counter("mxtpu_kvstore_push_total",
                                      "kvstore push calls completed"),
            "pull": _tmetrics.counter("mxtpu_kvstore_pull_total",
                                      "kvstore pull calls completed"),
            "retry": _tmetrics.counter(
                "mxtpu_kvstore_retries_total",
                "kvstore reconnect/resend attempts"),
            "reconnect": _tmetrics.counter(
                "mxtpu_kvstore_reconnects_total",
                "kvstore client reconnections"),
        }
        deadline = time.time() + timeout
        last = None
        while True:
            try:
                self._connect()
                break
            except OSError as e:  # server not up yet: retry (worker launch
                last = e           # order is unordered, like ps-lite's van)
                if time.time() > deadline:
                    raise MXNetError(
                        f"cannot reach async PS at {host}:{port}: {last}")
                time.sleep(0.1)
        self._lock = make_lock("_Client._lock")

    def _connect(self) -> None:
        self.close()
        self._sock = socket.create_connection((self._host, self._port),
                                              timeout=5.0)
        self._sock.settimeout(_io_timeout())

    def call(self, *msg):
        op = msg[0]
        key = msg[1] if len(msg) > 1 and not isinstance(
            msg[1], (bytes, bytearray)) else None
        # the trace context rides the wire as a trailing meta element the
        # server pops off — push/pull only (init/set_optimizer/stats are
        # setup, not steady state). Ids propagate whenever a context or
        # step is active — an UNSAMPLED trace still carries its ids (the
        # documented contract: sampling gates recording, not
        # propagation, so the server's resend/timeline events stay
        # step- and trace-attributed for unsampled traffic) — while the
        # client span that RECORDS the hop only opens when sampled
        ctx = _trace.current()
        sp = None
        if op in ("push", "pull"):
            step = _tele.current_step()
            if ctx is not None and ctx.sampled:
                sp = _trace.start_span(f"kvstore.{op}", kind="client",
                                       key=key)
            wire = _trace.to_wire(sp.ctx if sp is not None else ctx)
            if wire is not None or step is not None:
                msg = msg + ({"_meta": 1, "trace": wire, "step": step},)
        try:
            return self._call_locked(op, key, msg, sp)
        except BaseException as e:
            if sp is not None:
                sp.finish(error=type(e).__name__)
            raise
        finally:
            if sp is not None:
                sp.finish()

    def _call_locked(self, op, key, msg, sp):
        # the client lock deliberately serializes the SOCKET (one
        # request/reply in flight per connection, like ps-lite's van);
        # blocking I/O under it is the design
        with self._lock:  # mxlint: disable=MX803
            if op == "push" and len(msg) >= 5 and msg[4] is None:
                # stamp the version under the SAME lock that serializes
                # sends: assigned any earlier, concurrent pushers could
                # deliver versions out of order and the server's monotone
                # dedupe would drop real updates as resends
                msg = msg[:4] + (next(self._ver),) + msg[5:]
            if _inject.should("kv_drop"):   # chaos: sever before the call
                self.close()
            _inject.maybe_delay("kv_delay")

            def attempt():
                if self._sock is None:
                    self._connect()
                _send_msg(self._sock, msg)
                return _recv_msg(self._sock)

            def on_retry(n, exc):
                # reconnect + resend is the fault path worth a timeline
                # entry: a flapping PS shows up as a retry/reconnect
                # stream correlated with the training step
                _tele.emit("kvstore", severity="warning", op="retry",
                           target_op=op, key=key, attempt=n,
                           error=f"{type(exc).__name__}: {exc}")
                self._m["retry"].inc()
                if sp is not None:   # the span tells the resend story
                    sp.attrs["retries"] = n
                self.close()   # force a fresh connection before resending
                self._connect()
                self._m["reconnect"].inc()

            try:
                resp = attempt()
            except self._retry.retry_on:
                self.close()
                from ..fault.retry import call_with_retry
                try:
                    resp = call_with_retry(
                        attempt, self._retry, on_retry=on_retry,
                        describe=f"async PS {op!r} (key {key!r}) at "
                                 f"{self._host}:{self._port}")
                except RetryExhausted as e:
                    self.close()
                    _tele.emit("kvstore", severity="error", op=op,
                               key=key, error=str(e.last))
                    raise MXNetError(str(e)) from e.last
        if resp[0] != "ok":
            raise MXNetError(
                f"async PS {op!r} (key {key!r}) failed: "
                + (resp[1] if len(resp) > 1 else "unknown server error"))
        if op in ("push", "pull"):
            _tele.emit("kvstore", op=op, key=key)
            self._m[op].inc()
        return resp[1] if len(resp) > 1 else None

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None


class AsyncKVStore(GradientCompressionMixin, KVStoreBase):
    """``mx.kv.create('dist_async')`` (reference: kvstore_dist.h async mode).

    Rank 0 hosts :class:`AsyncPSServer`; every rank (including 0) talks to
    it through a socket client. ``push`` is handled at the server the
    moment it arrives — concurrent workers interleave in arrival order, and
    ``pull`` observes the freshest state with NO barrier anywhere. Worker
    topology comes from the dmlc-compatible env (``DMLC_NUM_WORKER`` /
    ``DMLC_WORKER_ID`` / ``DMLC_PS_ROOT_URI``, SURVEY §2.5); single-process
    use spins up a local server — same semantics, one worker.
    """

    #: offset from the rendezvous port so the PS socket never collides with
    #: the jax.distributed coordinator sharing DMLC_PS_ROOT_URI
    PORT_OFFSET = 17

    def __init__(self, optimizer=None):
        self._rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
        self._num = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        self._server: Optional[AsyncPSServer] = None
        self._compression: Dict = {}
        self._residuals: Dict = {}
        if uri and self._num > 1:
            port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9000")) + \
                self.PORT_OFFSET
            if self._rank == 0:
                self._server = AsyncPSServer(host="0.0.0.0", port=port)
            self._client = _Client(uri, port)
        else:
            self._server = AsyncPSServer()
            self._client = _Client("127.0.0.1", self._server.port)
        #: identity stamped on every push (the client adds the monotone
        #: version) so server-side dedupe makes retried pushes exactly-once
        self._wid = f"{self._rank}:{os.getpid()}:{id(self):x}"
        if optimizer is not None:
            self.set_optimizer(optimizer)

    # -- identity -----------------------------------------------------------
    @property
    def type(self) -> str:
        return "dist_async"

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._num

    # -- core ops -----------------------------------------------------------
    def _keys(self, key):
        return key if isinstance(key, (list, tuple)) else [key]

    def _vals(self, key, value):
        if isinstance(key, (list, tuple)):
            if len(key) != len(value):
                raise MXNetError("key list and value list length mismatch")
            return list(value)
        return [value]

    def _merge(self, k, v) -> onp.ndarray:
        """Device-local replica sum (per-replica compression first, exactly
        as KVStore.push orders it); the cross-WORKER story is the server's
        arrival-order handling — no all-reduce, no barrier."""
        vlist = v if isinstance(v, (list, tuple)) else [v]
        parts = [self._compress(k, i, x._data) for i, x in enumerate(vlist)]
        total = parts[0]
        for x in parts[1:]:
            total = total + x.astype(total.dtype)
        return onp.asarray(total)

    def init(self, key, value):
        for k, v in zip(self._keys(key), self._vals(key, value)):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._client.call("init", k, v.asnumpy())

    def push(self, key, value, priority: int = 0):
        for k, v in zip(self._keys(key), self._vals(key, value)):
            self._client.call("push", k, self._merge(k, v),
                              self._wid, None)  # client stamps the version

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True):
        results = [NDArray(self._client.call("pull", k))
                   for k in self._keys(key)]
        if out is not None:
            outs = out if isinstance(key, (list, tuple)) else [out]
            for o, r in zip(outs, results):
                for oo in (o if isinstance(o, (list, tuple)) else [o]):
                    oo._set_data(r._data.astype(oo.dtype))
            return out
        return results if isinstance(key, (list, tuple)) else results[0]

    def set_optimizer(self, optimizer) -> None:
        """Ship the optimizer to the server (reference: the pickled
        optimizer sent through ps-lite's control channel for server-side
        DataHandleEx updates). Accepts a name string like the sync store."""
        from .. import optimizer as opt_mod
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self._client.call("set_optimizer", pickle.dumps(optimizer))

    def stats(self) -> dict:
        return self._client.call("stats")

    def close(self) -> None:
        self._client.close()
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

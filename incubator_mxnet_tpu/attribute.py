"""Attribute scoping for symbol construction.

Reference counterpart: ``python/mxnet/attribute.py (AttrScope)`` — symbols
composed inside ``with mx.AttrScope(ctx_group='dev1'):`` carry the scope's
attributes (the mechanism behind ``group2ctx`` manual model parallelism,
``lr_mult``/``wd_mult`` annotations, and subgraph backend hints). Scope
attributes are stored on the node under an ``_attr_`` key prefix so they
never collide with operator parameters; ``Symbol.attr``/``list_attr`` strip
the prefix transparently.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["AttrScope", "current_attrs"]

_PREFIX = "_attr_"


class AttrScope:
    """Attach ``key=value`` string attributes to every symbol created inside
    the ``with`` block. Scopes nest; inner values win."""

    _local = threading.local()

    def __init__(self, **attrs: str):
        for k, v in attrs.items():
            if not isinstance(v, str):
                raise ValueError(
                    f"AttrScope value for {k!r} must be a string, got "
                    f"{type(v).__name__} (reference parity: attrs are "
                    "serialized as strings)")
        self._attrs = attrs

    @classmethod
    def _stack(cls):
        if not hasattr(cls._local, "stack"):
            cls._local.stack = []
        return cls._local.stack

    def __enter__(self):
        self._stack().append(self._attrs)
        return self

    def __exit__(self, *exc):
        self._stack().pop()


def current_attrs() -> Dict[str, str]:
    """Merged scope attributes, outermost first, keyed with the storage
    prefix (used by ``Symbol.__init__``)."""
    merged: Dict[str, str] = {}
    for frame in AttrScope._stack():
        for k, v in frame.items():
            merged[_PREFIX + k] = v
    return merged

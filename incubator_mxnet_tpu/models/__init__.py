"""Model zoo — the in-framework counterpart of the GluonCV/GluonNLP workloads
named in BASELINE.json (SURVEY §2.9): BERT pretraining, Transformer NMT,
image classification (LeNet/ResNet...), detection (SSD).

All models are HybridBlocks: eager for debugging, one ``hybridize()`` away
from a single XLA computation, and shardable over the parallel mesh with the
per-family ``*_sharding_rules()`` helpers.
"""
from . import transformer  # noqa: F401
from . import bert  # noqa: F401
from . import lenet  # noqa: F401
from .lenet import LeNet  # noqa: F401
from . import nmt  # noqa: F401
from .nmt import NMTModel, beam_search  # noqa: F401
from . import ssd  # noqa: F401
from .ssd import SSD, SSDTargetLoss  # noqa: F401
from . import rcnn  # noqa: F401
from .rcnn import FasterRCNN, RPN, FasterRCNNTargetLoss  # noqa: F401
from .transformer import (  # noqa: F401
    MultiHeadAttention, PositionwiseFFN, TransformerEncoderCell,
    StackedTransformerEncoder,
)
from .bert import (  # noqa: F401
    BERTModel, BERTEncoder, bert_sharding_rules, get_bert, bert_pretrain_loss,
)

#: Serving axis specs per model family — the ``input_axes``/``pad_values``
#: a ``serve.CompiledModel``/``ModelRegistry.load`` needs to bucket each
#: input correctly. Indexed by the *call signature* the family's serving
#: forward uses; ``valid_length`` pads with 0 so attention masks the fake
#: rows/positions (padding never leaks into real outputs).
SERVE_SPECS = {
    # BERTModel(ids, token_types, valid_length, masked_positions)
    "bert": {
        "input_axes": [{0: "batch", 1: "seq"}, {0: "batch", 1: "seq"},
                       {0: "batch"}, {0: "batch"}],
        "output_axes": [{0: "batch", 1: "seq"}, {0: "batch"},
                        {0: "batch"}, {0: "batch"}],
        "pad_values": [0, 0, 0, 0],
    },
    # BERTModel(ids, token_types, valid_length) with use_decoder=False,
    # use_classifier=False — encoder+pooler serving (embedding backends)
    "bert_encoder": {
        "input_axes": [{0: "batch", 1: "seq"}, {0: "batch", 1: "seq"},
                       {0: "batch"}],
        "output_axes": [{0: "batch", 1: "seq"}, {0: "batch"}],
        "pad_values": [0, 0, 0],
    },
    # LeNet(images) — fixed spatial dims, bucketed batch only
    "lenet": {
        "input_axes": [{0: "batch"}],
        "output_axes": [{0: "batch"}],
        "pad_values": [0],
    },
    # StackedTransformerEncoder(x, mask=None) served unmasked
    "transformer_encoder": {
        "input_axes": [{0: "batch", 1: "seq"}],
        "output_axes": [{0: "batch", 1: "seq"}],
        "pad_values": [0],
    },
    # NMTModel.encode(src_ids, src_len) — the beam-search entry's encoder
    "nmt_encoder": {
        "input_axes": [{0: "batch", 1: "seq"}, {0: "batch"}],
        "output_axes": [{0: "batch", 1: "seq"}],
        "pad_values": [0, 0],
    },
}


def serve_spec(family: str) -> dict:
    """Copy of the named serving spec (see :data:`SERVE_SPECS`)."""
    if family not in SERVE_SPECS:
        raise KeyError(f"no serving spec for {family!r}; known: "
                       f"{sorted(SERVE_SPECS)}")
    spec = SERVE_SPECS[family]
    return {"input_axes": [dict(a) for a in spec["input_axes"]],
            "output_axes": [dict(a) for a in spec["output_axes"]],
            "pad_values": list(spec["pad_values"])}

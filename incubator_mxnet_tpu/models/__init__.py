"""Model zoo — the in-framework counterpart of the GluonCV/GluonNLP workloads
named in BASELINE.json (SURVEY §2.9): BERT pretraining, Transformer NMT,
image classification (LeNet/ResNet...), detection (SSD).

All models are HybridBlocks: eager for debugging, one ``hybridize()`` away
from a single XLA computation, and shardable over the parallel mesh with the
per-family ``*_sharding_rules()`` helpers.
"""
from ..gluon.block import HybridBlock
from . import transformer  # noqa: F401
from . import bert  # noqa: F401
from . import lenet  # noqa: F401
from .lenet import LeNet  # noqa: F401
from . import nmt  # noqa: F401
from .nmt import NMTModel, beam_search, beam_search_reference  # noqa: F401
from . import ssd  # noqa: F401
from .ssd import SSD, SSDTargetLoss  # noqa: F401
from . import rcnn  # noqa: F401
from .rcnn import FasterRCNN, RPN, FasterRCNNTargetLoss  # noqa: F401
from .transformer import (  # noqa: F401
    MultiHeadAttention, PositionwiseFFN, TransformerEncoderCell,
    StackedTransformerEncoder,
)
from .bert import (  # noqa: F401
    BERTModel, BERTEncoder, bert_sharding_rules, get_bert, bert_pretrain_loss,
)

#: Serving axis specs per model family — the ``input_axes``/``pad_values``
#: a ``serve.CompiledModel``/``ModelRegistry.load`` needs to bucket each
#: input correctly. Indexed by the *call signature* the family's serving
#: forward uses; ``valid_length`` pads with 0 so attention masks the fake
#: rows/positions (padding never leaks into real outputs).
SERVE_SPECS = {
    # BERTModel(ids, token_types, valid_length, masked_positions)
    "bert": {
        "input_axes": [{0: "batch", 1: "seq"}, {0: "batch", 1: "seq"},
                       {0: "batch"}, {0: "batch"}],
        "output_axes": [{0: "batch", 1: "seq"}, {0: "batch"},
                        {0: "batch"}, {0: "batch"}],
        "pad_values": [0, 0, 0, 0],
    },
    # BERTModel(ids, token_types, valid_length) with use_decoder=False,
    # use_classifier=False — encoder+pooler serving (embedding backends)
    "bert_encoder": {
        "input_axes": [{0: "batch", 1: "seq"}, {0: "batch", 1: "seq"},
                       {0: "batch"}],
        "output_axes": [{0: "batch", 1: "seq"}, {0: "batch"}],
        "pad_values": [0, 0, 0],
    },
    # LeNet(images) — fixed spatial dims, bucketed batch only
    "lenet": {
        "input_axes": [{0: "batch"}],
        "output_axes": [{0: "batch"}],
        "pad_values": [0],
    },
    # StackedTransformerEncoder(x, mask=None) served unmasked
    "transformer_encoder": {
        "input_axes": [{0: "batch", 1: "seq"}],
        "output_axes": [{0: "batch", 1: "seq"}],
        "pad_values": [0],
    },
    # NMTModel.encode(src_ids, src_len) — the beam-search entry's encoder
    "nmt_encoder": {
        "input_axes": [{0: "batch", 1: "seq"}, {0: "batch"}],
        "output_axes": [{0: "batch", 1: "seq"}],
        "pad_values": [0, 0],
    },
}


#: Families whose smoke model actually contains quantizable layers
#: (``nn.Dense``/``nn.Conv2D`` children the int8 graph pass can swap).
#: ``transformer_encoder`` is excluded: its stacked-parameter scan
#: encoder has no per-layer Dense children, so its "quantized" twin
#: would be a float copy. This is the quantized zoo every int8 consumer
#: iterates (``mxlint --hlo --quantized``, ``serve_bench --int8``,
#: ``bench.py --proxy`` int8 records, ``benchmark/int8_probe.py``).
QUANT_FAMILIES = ("bert", "bert_encoder", "lenet", "nmt_encoder")


def serve_spec(family: str) -> dict:
    """Copy of the named serving spec (see :data:`SERVE_SPECS`)."""
    if family not in SERVE_SPECS:
        raise KeyError(f"no serving spec for {family!r}; known: "
                       f"{sorted(SERVE_SPECS)}")
    spec = SERVE_SPECS[family]
    return {"input_axes": [dict(a) for a in spec["input_axes"]],
            "output_axes": [dict(a) for a in spec["output_axes"]],
            "pad_values": list(spec["pad_values"])}


class _NMTEncodeEntry(HybridBlock):
    """The ``nmt_encoder`` serving entry as a traceable block: the
    embed → masked-encoder half of ``NMTModel.encode``, built WITHOUT the
    decoder so the serving signature carries no dead decoder parameters
    (analysis.hlo MX703 would rightly flag them)."""

    def __init__(self, src_vocab=100, units=32, hidden_size=64,
                 num_layers=2, num_heads=2, max_length=32, **kw):
        super().__init__(**kw)
        from ..gluon import nn
        from .nmt import TransformerEncoder
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units,
                                          prefix="src_embed_")
            self.encoder = TransformerEncoder(units, hidden_size,
                                              num_layers, num_heads, 0.1,
                                              max_length, prefix="enc_")

    def hybrid_forward(self, F, src, src_len):
        B, L = src.shape
        steps = F.arange(0, L, dtype="float32").reshape((1, L))
        mask = F.broadcast_lesser(steps, src_len.reshape((B, 1)))
        return self.encoder(self.src_embed(src),
                            mask.reshape((B, 1, 1, L)))


def hlo_smoke(family: str, batch: int = None, seq: int = None) -> dict:
    """Small live instance of one serving family for compiled-graph
    analysis (``mxlint --hlo`` / CI ``hlo-lint``): returns ``{"block",
    "example_args", "table", "spec", "compiled"}`` sized so every bucket
    traces in milliseconds on CPU. ``compiled`` is THE un-warmed
    ``serve.CompiledModel`` every gate analyzes (building it never
    XLA-compiles — only :meth:`~...serve.CompiledModel.warmup` does), so
    the CLI target and the tests provably check the same object shape.

    ``batch``/``seq`` override the bucket geometry with a SINGLE bucket
    of that size (example args sized to fill it) — the knob
    ``benchmark.autotune`` turns to price batch/bucket-geometry
    candidates through the exact entry the gates analyze. Defaults keep
    the historical two-bucket ladders, so every existing caller traces
    byte-identical graphs."""
    import numpy as onp

    from .. import nd, serve

    spec = serve_spec(family)
    B = int(batch) if batch else 2
    batch_lad = (int(batch), int(batch)) if batch else (1, 4)
    L = int(seq) if seq else 16
    seq_lad = (int(seq), int(seq)) if seq else (8, 16)
    if family in ("bert", "bert_encoder"):
        vocab, P = 1000, 4
        if L > 32:
            raise ValueError(f"hlo_smoke({family!r}) probe caps seq at 32 "
                             f"(position table), got {L}")
        net = get_bert("bert_2_128_2", vocab_size=vocab, max_length=32,
                       dropout=0.1, use_decoder=(family == "bert"),
                       use_classifier=(family == "bert"))
        net.initialize()
        net.hybridize()
        ids = nd.array(onp.ones((B, L), "int32"))
        tt = nd.array(onp.zeros((B, L), "int32"))
        vl = nd.array(onp.full((B,), L, "float32"))
        if family == "bert":
            pos = nd.array(onp.zeros((B, P), "int32"))
            args = (ids, tt, vl, pos)
        else:
            args = (ids, tt, vl)
        table = serve.BucketTable({"batch": batch_lad, "seq": seq_lad})
    elif family == "lenet":
        net = LeNet()
        net.initialize()
        net.hybridize()
        args = (nd.array(onp.zeros((B, 1, 28, 28), "float32")),)
        table = serve.BucketTable({"batch": batch_lad})
    elif family == "transformer_encoder":
        net = StackedTransformerEncoder(num_layers=2, units=32,
                                        hidden_size=64, num_heads=2)
        net.initialize()
        net.hybridize()
        args = (nd.array(onp.zeros((B, L, 32), "float32")),)
        table = serve.BucketTable({"batch": batch_lad, "seq": seq_lad})
    elif family == "nmt_encoder":
        if L > 32:
            raise ValueError(f"hlo_smoke({family!r}) probe caps seq at 32 "
                             f"(position table), got {L}")
        net = _NMTEncodeEntry()
        net.initialize()
        net.hybridize()
        args = (nd.array(onp.ones((B, L), "int32")),
                nd.array(onp.full((B,), L, "float32")))
        table = serve.BucketTable({"batch": batch_lad, "seq": seq_lad})
    else:
        raise KeyError(f"no hlo smoke model for {family!r}; known: "
                       f"{sorted(SERVE_SPECS)}")
    net(*args)
    compiled = serve.CompiledModel(net, table, spec["input_axes"],
                                   example_args=args,
                                   output_axes=spec["output_axes"],
                                   pad_values=spec["pad_values"],
                                   autotune_key=family)
    return {"block": net, "example_args": args, "table": table,
            "spec": spec, "compiled": compiled}


def calib_args(family: str, batch: int = None, seq: int = None,
               seed: int = 0) -> tuple:
    """Seeded non-degenerate inputs for ``family``'s serving signature —
    the calibration batch :func:`quantized_smoke` observes. The zoo's
    ``hlo_smoke`` example args are mostly zeros (fine for tracing,
    useless for calibration: every range collapses), so calibration data
    is drawn separately: float tensors ~N(0,1), ids uniform over the
    probe vocab, valid lengths full."""
    import numpy as onp

    from .. import nd

    sm_args = hlo_smoke(family, batch=batch, seq=seq)["example_args"]
    rs = onp.random.RandomState(seed)
    out = []
    for a in sm_args:
        arr = onp.asarray(a.asnumpy())
        if arr.dtype.kind == "f":
            if arr.ndim == 1:          # valid_length-style: keep full
                out.append(nd.array(arr))
            else:
                out.append(nd.array(
                    rs.randn(*arr.shape).astype(arr.dtype)))
        else:                          # ids: uniform over the probe vocab
            hi = max(int(arr.max()) + 1, 32)
            out.append(nd.array(
                rs.randint(0, hi, arr.shape).astype(arr.dtype)))
    return tuple(out)


def quantized_smoke(family: str, batch: int = None, seq: int = None,
                    percentile: float = None) -> dict:
    """The quantized twin of :func:`hlo_smoke`: calibrate the family's
    smoke model on a seeded batch (:func:`calib_args` →
    ``quantization.observe_net``) and lower the Observer through
    ``quantization.quantize_model`` into a quantized
    ``serve.CompiledModel`` sharing the float model's bucket table,
    axes, pad values, and ``autotune_key``.

    This is THE quantized-zoo entry every int8 consumer analyzes —
    ``mxlint --hlo --quantized``, the autotune ``quantize`` dimension,
    ``serve_bench --int8``, ``benchmark/int8_probe.py``, and the
    ``<family>_int8`` proxy records — so the graphs CI lints, the graphs
    the roofline prices, and the graphs the bench runs are provably the
    same. Deterministic: same family/geometry → byte-identical int8
    weights and ranges.

    Returns ``{"block", "example_args", "table", "spec", "compiled",
    "observer", "f32"}`` — ``compiled`` is the quantized model,
    ``f32`` the full float ``hlo_smoke`` dict it was derived from.
    """
    from .. import quantization as _quant

    sm = hlo_smoke(family, batch=batch, seq=seq)
    cargs = calib_args(family, batch=batch, seq=seq)
    observer = _quant.observe_net(sm["block"], [cargs])
    qcm = _quant.quantize_model(sm["compiled"], observer,
                                percentile=percentile)
    return {"block": qcm._block, "example_args": sm["example_args"],
            "table": sm["table"], "spec": sm["spec"], "compiled": qcm,
            "observer": observer, "f32": sm}

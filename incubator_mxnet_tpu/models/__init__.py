"""Model zoo — the in-framework counterpart of the GluonCV/GluonNLP workloads
named in BASELINE.json (SURVEY §2.9): BERT pretraining, Transformer NMT,
image classification (LeNet/ResNet...), detection (SSD).

All models are HybridBlocks: eager for debugging, one ``hybridize()`` away
from a single XLA computation, and shardable over the parallel mesh with the
per-family ``*_sharding_rules()`` helpers.
"""
from . import transformer  # noqa: F401
from . import bert  # noqa: F401
from . import lenet  # noqa: F401
from .lenet import LeNet  # noqa: F401
from . import nmt  # noqa: F401
from .nmt import NMTModel, beam_search  # noqa: F401
from . import ssd  # noqa: F401
from .ssd import SSD, SSDTargetLoss  # noqa: F401
from . import rcnn  # noqa: F401
from .rcnn import FasterRCNN, RPN, FasterRCNNTargetLoss  # noqa: F401
from .transformer import (  # noqa: F401
    MultiHeadAttention, PositionwiseFFN, TransformerEncoderCell,
    StackedTransformerEncoder,
)
from .bert import (  # noqa: F401
    BERTModel, BERTEncoder, bert_sharding_rules, get_bert, bert_pretrain_loss,
)

"""Faster-RCNN model family (reference: GluonCV ``model_zoo/faster_rcnn`` +
the RPN path of ``src/operator/contrib/multi_proposal.cu``; SURVEY §2.9 names
Faster-RCNN as a BASELINE.json workload).

TPU-native design: the whole two-stage pipeline is FIXED-SHAPE — RPN scores →
padded top-k → greedy-NMS scan emits exactly ``rpn_post_nms_top_n`` rois
(zero-padded when exhausted), ROIAlign gathers static sampling grids, and the
per-roi head is a batched matmul over ``B·R`` rois. One ``hybridize()`` away
from a single XLA computation with no dynamic shapes anywhere.
"""
from __future__ import annotations

from typing import Sequence, Tuple

from ..gluon.block import HybridBlock
from ..gluon import nn
from .. import initializer as _init

__all__ = ["FasterRCNN", "RPN", "FasterRCNNTargetLoss"]


class _Backbone(HybridBlock):
    """Small conv trunk standing in for VGG/ResNet-C4 (swap any feature
    extractor with the same (B, C, H/stride, W/stride) contract)."""

    def __init__(self, filters: Sequence[int] = (16, 32, 64), **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.blocks = []
            for i, f in enumerate(filters):
                conv = nn.Conv2D(f, 3, padding=1, activation="relu",
                                 prefix=f"conv{i}_")
                pool = nn.MaxPool2D(2, 2)
                self.register_child(conv, f"conv{i}")
                self.register_child(pool, f"pool{i}")
                self.blocks += [conv, pool]

    def hybrid_forward(self, F, x):
        for b in self.blocks:
            x = b(x)
        return x


class RPN(HybridBlock):
    """Region proposal network head (reference: rcnn/rpn). Produces
    objectness (B, 2A, H, W) and box deltas (B, 4A, H, W), then the
    fixed-shape ``MultiProposal`` op turns them into rois."""

    def __init__(self, channels: int, num_anchors: int, **kw):
        super().__init__(**kw)
        self._A = num_anchors
        with self.name_scope():
            # Normal(0.01) heads (reference: GluonCV rpn.py
            # weight_initializer=mx.init.Normal(0.01)): tiny initial
            # weights keep objectness near-uniform and deltas near zero, so
            # proposals start AT the anchors — Xavier-scale heads can start
            # the regression far enough off that the RPN never recovers
            # (observed: seed-dependent localization collapse)
            self.conv = nn.Conv2D(channels, 3, padding=1, activation="relu",
                                  prefix="conv_",
                                  weight_initializer=_init.Normal(0.01))
            self.cls = nn.Conv2D(2 * num_anchors, 1, prefix="cls_",
                                 weight_initializer=_init.Normal(0.01))
            self.reg = nn.Conv2D(4 * num_anchors, 1, prefix="reg_",
                                 weight_initializer=_init.Normal(0.01))

    def hybrid_forward(self, F, x):
        h = self.conv(x)
        scores = self.cls(h)
        B = scores.shape[0]
        A, H, W = self._A, scores.shape[2], scores.shape[3]
        # softmax over {bg, fg} per anchor (reference applies softmax over
        # the reshaped (2, A*H*W) axis before Proposal)
        s = F.softmax(scores.reshape((B, 2, A, H, W)), axis=1)
        return s.reshape((B, 2 * A, H, W)), self.reg(h)


class FasterRCNN(HybridBlock):
    """Two-stage detector.

    ``forward(x, im_info)`` → ``(cls_scores (B, R, num_classes+1),
    box_deltas (B, R, 4·(num_classes+1)), rois (B·R, 5))`` with
    ``R = rpn_post_nms_top_n`` — every output fixed-shape.
    """

    def __init__(self, num_classes: int,
                 scales: Tuple[float, ...] = (2, 4),
                 ratios: Tuple[float, ...] = (0.5, 1, 2),
                 feature_stride: int = 8,
                 rpn_pre_nms_top_n: int = 64,
                 rpn_post_nms_top_n: int = 16,
                 rpn_min_size: int = 2,
                 roi_size: Tuple[int, int] = (7, 7),
                 backbone_filters: Sequence[int] = (16, 32, 64),
                 output_rpn: bool = False, **kw):
        super().__init__(**kw)
        self._output_rpn = output_rpn
        self._num_classes = num_classes
        self._scales, self._ratios = tuple(scales), tuple(ratios)
        self._stride = feature_stride
        self._pre, self._post = rpn_pre_nms_top_n, rpn_post_nms_top_n
        self._min_size = rpn_min_size
        self._roi_size = tuple(roi_size)
        A = len(scales) * len(ratios)
        with self.name_scope():
            self.backbone = _Backbone(backbone_filters, prefix="backbone_")
            self.rpn = RPN(backbone_filters[-1], A, prefix="rpn_")
            self.head_dense = nn.Dense(128, activation="relu",
                                       prefix="head_", flatten=False)
            # reference head init (GluonCV faster_rcnn.py): cls
            # Normal(0.01), bbox Normal(0.001) — box deltas start at zero
            self.cls_score = nn.Dense(num_classes + 1, prefix="cls_score_",
                                      flatten=False,
                                      weight_initializer=_init.Normal(0.01))
            self.bbox_pred = nn.Dense(4 * (num_classes + 1),
                                      prefix="bbox_pred_", flatten=False,
                                      weight_initializer=_init.Normal(0.001))

    def hybrid_forward(self, F, x, im_info, gt=None):
        feat = self.backbone(x)
        rpn_cls, rpn_reg = self.rpn(feat)
        rois = F.MultiProposal(
            rpn_cls, rpn_reg, im_info,
            rpn_pre_nms_top_n=self._pre, rpn_post_nms_top_n=self._post,
            rpn_min_size=self._min_size, scales=self._scales,
            ratios=self._ratios, feature_stride=self._stride)
        # proposals are training CONSTANTS for the head (reference: the
        # Proposal op registers no gradient) — without this, box gradients
        # would leak into the RPN through roi coordinates
        rois = F.BlockGrad(rois)
        B = x.shape[0]
        R = self._post
        if gt is not None:
            # training: append the gt boxes to the roi set so the head
            # always sees perfect positives (reference proposal_target.py
            # vstacks gt_boxes onto the sampled rois) — padding gt rows
            # (cls -1) become zero-area rois at the origin, matched as
            # background like the RPN's NMS padding
            M = gt.shape[1]
            valid = F.broadcast_greater_equal(
                F.slice_axis(gt, axis=2, begin=0, end=1),
                F.zeros_like(F.slice_axis(gt, axis=2, begin=0, end=1)))
            gt_boxes = F.slice_axis(gt, axis=2, begin=1, end=5) * valid
            rois = F.reshape(rois, (B, R, 5))
            batch_col = F.slice_axis(rois, axis=2, begin=0,
                                     end=1)               # (B, R, 1)
            gt_bidx = F.slice_axis(batch_col, axis=1, begin=0,
                                   end=1)                 # (B, 1, 1)
            gt_bidx = F.broadcast_axis(gt_bidx, axis=1, size=M)
            gt_rois = F.concat(gt_bidx, gt_boxes, dim=2)  # (B, M, 5)
            rois = F.reshape(F.concat(rois, gt_rois, dim=1),
                             (B * (R + M), 5))
            R = R + M
        pooled = F.ROIAlign(feat, rois, pooled_size=self._roi_size,
                            spatial_scale=1.0 / self._stride,
                            sample_ratio=2)                 # (B·R, C, PH, PW)
        flat = pooled.reshape((B * R, -1))
        h = self.head_dense(flat)
        cls = F.softmax(self.cls_score(h), axis=-1).reshape(
            (B, R, self._num_classes + 1))
        box = self.bbox_pred(h).reshape((B, R, 4 * (self._num_classes + 1)))
        if self._output_rpn:
            # training mode (reference returns the rpn raw outputs group
            # for the AnchorTarget losses)
            return cls, box, rois, rpn_cls, rpn_reg
        return cls, box, rois

    def detect(self, x, im_info, threshold=0.05, nms_threshold=0.3,
               nms_topk=-1):
        """Full inference: forward + per-class decode + NMS → (B, R·C, 6)
        ``[class_id, score, x1, y1, x2, y2]`` in pixels, -1 rows invalid
        (reference: the test-time decode of GluonCV faster_rcnn over the
        class-specific ``bbox_pred`` slots)."""
        from .. import autograd
        import jax.numpy as jnp
        from ..ndarray import NDArray
        from ..ops.detection import _bbox_pred, _clip_boxes, box_nms

        with autograd.predict_mode():
            out = self(x, im_info)
        cls, box, rois = out[0], out[1], out[2]
        B, R = x.shape[0], self._post
        C = self._num_classes
        probs = cls._data                                  # (B, R, C+1)
        deltas = box._data.reshape(B, R, C + 1, 4)[:, :, 1:, :]
        roib = rois._data.reshape(B, R, 5)[..., 1:5]
        info = im_info._data

        # one batched decode + one batched NMS (box_nms vmaps leading dims)
        anchors = jnp.broadcast_to(roib[:, :, None, :],
                                   (B, R, C, 4)).reshape(-1, 4)
        boxes = _bbox_pred(anchors, deltas.reshape(-1, 4)).reshape(B, R, C, 4)
        boxes = _clip_boxes(boxes, info[:, None, None, 0],
                            info[:, None, None, 1])
        ids = jnp.broadcast_to(
            jnp.arange(C, dtype=boxes.dtype)[None, None, :, None],
            (B, R, C, 1))
        rows = jnp.concatenate(
            [ids, probs[:, :, 1:, None], boxes], axis=-1)  # (B, R, C, 6)
        rows = rows.reshape(B, R * C, 6)
        dets = box_nms(rows, overlap_thresh=nms_threshold,
                       valid_thresh=threshold, topk=nms_topk,
                       coord_start=2, score_index=1, id_index=0,
                       force_suppress=False)
        return NDArray(dets, ctx=x.context)


class FasterRCNNTargetLoss(HybridBlock):
    """Two-stage training objective (reference: the RPN cls/box +
    RCNN cls/box loss group of GluonCV train_faster_rcnn.py, built on the
    AnchorTarget/ProposalTarget stages — ops/detection.py
    ``rpn_target``/``proposal_target``).

    ``forward(cls, box, rois, rpn_cls, rpn_reg, gt, im_info)`` with the
    net's 5-output training mode (``output_rpn=True``); ``gt (B, M, 5)``
    ``[cls, x1, y1, x2, y2]`` in PIXEL coords, -1 padded. Returns the
    scalar sum of the four normalized losses."""

    def __init__(self, num_classes: int,
                 scales=(2, 4), ratios=(0.5, 1, 2), feature_stride=8,
                 rpn_fg_overlap=0.7, rpn_bg_overlap=0.3, head_fg_overlap=0.5,
                 **kw):
        super().__init__(**kw)
        self._num_classes = num_classes
        self._scales, self._ratios = tuple(scales), tuple(ratios)
        self._stride = feature_stride
        self._rpn_fg, self._rpn_bg = rpn_fg_overlap, rpn_bg_overlap
        self._head_fg = head_fg_overlap

    def hybrid_forward(self, F, cls, box, rois, rpn_cls, rpn_reg, gt,
                       im_info):
        eps = 1e-8
        B, A2 = rpn_cls.shape[0], rpn_cls.shape[1]
        A = A2 // 2
        # ---- RPN stage (AnchorTarget) ----------------------------------
        lbl, rpn_t, rpn_m = F.rpn_target(
            rpn_cls, gt, im_info, feature_stride=self._stride,
            scales=self._scales, ratios=self._ratios,
            fg_overlap=self._rpn_fg, bg_overlap=self._rpn_bg)
        # probabilities in MultiProposal's (h, w, a) flat order
        p_bg = F.reshape(F.transpose(
            F.slice_axis(rpn_cls, axis=1, begin=0, end=A),
            axes=(0, 2, 3, 1)), (B, -1))
        p_fg = F.reshape(F.transpose(
            F.slice_axis(rpn_cls, axis=1, begin=A, end=2 * A),
            axes=(0, 2, 3, 1)), (B, -1))
        is_fg = F.equal(lbl, F.ones_like(lbl))
        is_bg = F.equal(lbl, F.zeros_like(lbl))
        rpn_cls_loss = -(is_fg * F.log(p_fg + eps)
                         + is_bg * F.log(p_bg + eps))
        n_lbl = F.sum(is_fg) + F.sum(is_bg) + 1.0
        rpn_cls_loss = F.sum(rpn_cls_loss) / n_lbl
        # deltas in the same flat order (B, HWA, 4)
        d = F.transpose(
            F.reshape(rpn_reg,
                      (B, A, 4, rpn_reg.shape[2], rpn_reg.shape[3])),
            axes=(0, 3, 4, 1, 2))
        d = F.reshape(d, (B, -1, 4))
        n_fg = F.sum(is_fg) + 1.0
        rpn_box_loss = F.sum(F.smooth_l1((d - rpn_t) * rpn_m,
                                         scalar=3.0)) / n_fg
        # ---- RCNN head stage (ProposalTarget) --------------------------
        cls_t, box_t, box_m = F.proposal_target(
            rois, gt, num_classes=self._num_classes,
            fg_overlap=self._head_fg)
        head_ce = -F.log(F.pick(cls, cls_t, axis=-1) + eps)  # (B, R)
        # class-balanced CE: background rois dominate the fixed-shape roi
        # set ~R:1, so fg and bg terms normalize separately (the reference
        # reaches the same balance by sampling rois at a 1:3 fg:bg ratio)
        head_is_fg = F.greater(cls_t, F.zeros_like(cls_t))
        head_is_bg = F.ones_like(head_is_fg) - head_is_fg
        n_head_fg = F.sum(head_is_fg) + 1.0
        n_head_bg = F.sum(head_is_bg) + 1.0
        head_cls_loss = F.sum(head_ce * head_is_fg) / n_head_fg \
            + F.sum(head_ce * head_is_bg) / n_head_bg
        head_box_loss = F.sum(F.smooth_l1((box - box_t) * box_m,
                                          scalar=1.0)) / n_head_fg
        return rpn_cls_loss + rpn_box_loss + head_cls_loss + head_box_loss

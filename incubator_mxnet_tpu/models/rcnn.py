"""Faster-RCNN model family (reference: GluonCV ``model_zoo/faster_rcnn`` +
the RPN path of ``src/operator/contrib/multi_proposal.cu``; SURVEY §2.9 names
Faster-RCNN as a BASELINE.json workload).

TPU-native design: the whole two-stage pipeline is FIXED-SHAPE — RPN scores →
padded top-k → greedy-NMS scan emits exactly ``rpn_post_nms_top_n`` rois
(zero-padded when exhausted), ROIAlign gathers static sampling grids, and the
per-roi head is a batched matmul over ``B·R`` rois. One ``hybridize()`` away
from a single XLA computation with no dynamic shapes anywhere.
"""
from __future__ import annotations

from typing import Sequence, Tuple

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["FasterRCNN", "RPN"]


class _Backbone(HybridBlock):
    """Small conv trunk standing in for VGG/ResNet-C4 (swap any feature
    extractor with the same (B, C, H/stride, W/stride) contract)."""

    def __init__(self, filters: Sequence[int] = (16, 32, 64), **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.blocks = []
            for i, f in enumerate(filters):
                conv = nn.Conv2D(f, 3, padding=1, activation="relu",
                                 prefix=f"conv{i}_")
                pool = nn.MaxPool2D(2, 2)
                self.register_child(conv, f"conv{i}")
                self.register_child(pool, f"pool{i}")
                self.blocks += [conv, pool]

    def hybrid_forward(self, F, x):
        for b in self.blocks:
            x = b(x)
        return x


class RPN(HybridBlock):
    """Region proposal network head (reference: rcnn/rpn). Produces
    objectness (B, 2A, H, W) and box deltas (B, 4A, H, W), then the
    fixed-shape ``MultiProposal`` op turns them into rois."""

    def __init__(self, channels: int, num_anchors: int, **kw):
        super().__init__(**kw)
        self._A = num_anchors
        with self.name_scope():
            self.conv = nn.Conv2D(channels, 3, padding=1, activation="relu",
                                  prefix="conv_")
            self.cls = nn.Conv2D(2 * num_anchors, 1, prefix="cls_")
            self.reg = nn.Conv2D(4 * num_anchors, 1, prefix="reg_")

    def hybrid_forward(self, F, x):
        h = self.conv(x)
        scores = self.cls(h)
        B = scores.shape[0]
        A, H, W = self._A, scores.shape[2], scores.shape[3]
        # softmax over {bg, fg} per anchor (reference applies softmax over
        # the reshaped (2, A*H*W) axis before Proposal)
        s = F.softmax(scores.reshape((B, 2, A, H, W)), axis=1)
        return s.reshape((B, 2 * A, H, W)), self.reg(h)


class FasterRCNN(HybridBlock):
    """Two-stage detector.

    ``forward(x, im_info)`` → ``(cls_scores (B, R, num_classes+1),
    box_deltas (B, R, 4·(num_classes+1)), rois (B·R, 5))`` with
    ``R = rpn_post_nms_top_n`` — every output fixed-shape.
    """

    def __init__(self, num_classes: int,
                 scales: Tuple[float, ...] = (2, 4),
                 ratios: Tuple[float, ...] = (0.5, 1, 2),
                 feature_stride: int = 8,
                 rpn_pre_nms_top_n: int = 64,
                 rpn_post_nms_top_n: int = 16,
                 rpn_min_size: int = 2,
                 roi_size: Tuple[int, int] = (7, 7),
                 backbone_filters: Sequence[int] = (16, 32, 64), **kw):
        super().__init__(**kw)
        self._num_classes = num_classes
        self._scales, self._ratios = tuple(scales), tuple(ratios)
        self._stride = feature_stride
        self._pre, self._post = rpn_pre_nms_top_n, rpn_post_nms_top_n
        self._min_size = rpn_min_size
        self._roi_size = tuple(roi_size)
        A = len(scales) * len(ratios)
        with self.name_scope():
            self.backbone = _Backbone(backbone_filters, prefix="backbone_")
            self.rpn = RPN(backbone_filters[-1], A, prefix="rpn_")
            self.head_dense = nn.Dense(128, activation="relu",
                                       prefix="head_", flatten=False)
            self.cls_score = nn.Dense(num_classes + 1, prefix="cls_score_",
                                      flatten=False)
            self.bbox_pred = nn.Dense(4 * (num_classes + 1),
                                      prefix="bbox_pred_", flatten=False)

    def hybrid_forward(self, F, x, im_info):
        feat = self.backbone(x)
        rpn_cls, rpn_reg = self.rpn(feat)
        rois = F.MultiProposal(
            rpn_cls, rpn_reg, im_info,
            rpn_pre_nms_top_n=self._pre, rpn_post_nms_top_n=self._post,
            rpn_min_size=self._min_size, scales=self._scales,
            ratios=self._ratios, feature_stride=self._stride)
        pooled = F.ROIAlign(feat, rois, pooled_size=self._roi_size,
                            spatial_scale=1.0 / self._stride,
                            sample_ratio=2)                 # (B·R, C, PH, PW)
        B = x.shape[0]
        R = self._post
        flat = pooled.reshape((B * R, -1))
        h = self.head_dense(flat)
        cls = F.softmax(self.cls_score(h), axis=-1).reshape(
            (B, R, self._num_classes + 1))
        box = self.bbox_pred(h).reshape((B, R, 4 * (self._num_classes + 1)))
        return cls, box, rois

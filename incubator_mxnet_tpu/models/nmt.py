"""Transformer NMT (encoder-decoder) with beam-search inference.

Reference parity: GluonNLP ``scripts/machine_translation`` /
``gluonnlp/model/transformer.py`` (Transformer-big WMT14 in BASELINE.json)
and the ``BeamSearchSampler`` inference path — SURVEY §2.9.

TPU-native design: training is teacher-forced full-sequence (one MXU-heavy
pass, causal flash attention); beam search decodes with a **static-shape
loop** (``lax.while_loop`` over max_length with a fixed beam) instead of the
reference's dynamic-length Python loop, so the whole decode jit-compiles.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..gluon.block import HybridBlock
from ..gluon import nn
from .transformer import MultiHeadAttention, PositionwiseFFN

__all__ = ["TransformerEncoder", "TransformerDecoder", "NMTModel",
           "beam_search", "beam_search_reference",
           "incremental_decode_params", "cross_attention_kv",
           "nmt_step", "nmt_paged_step", "transformer_sharding_rules"]


import functools


@functools.lru_cache(maxsize=16)
def _position_encoding(L, C, dtype=jnp.float32):
    # cached: rebuilt tables would otherwise cost a host round-trip on every
    # forward (beam search calls the decoder max_length times)
    pos = onp.arange(L)[:, None]
    dim = onp.arange(C // 2)[None, :]
    angle = pos / onp.power(10000.0, 2 * dim / C)
    out = onp.zeros((L, C), "float32")
    out[:, 0::2] = onp.sin(angle)
    out[:, 1::2] = onp.cos(angle)
    return jnp.asarray(out, dtype)


class _EncoderLayer(HybridBlock):
    def __init__(self, units, hidden, heads, dropout, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.attn = MultiHeadAttention(units, heads, dropout=dropout,
                                           prefix="attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.attn(x, None, mask))
        return self.ln2(x + self.ffn(x))


class _DecoderLayer(HybridBlock):
    def __init__(self, units, hidden, heads, dropout, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.self_attn = MultiHeadAttention(units, heads, dropout=dropout,
                                                causal=True, prefix="selfattn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.cross_attn = MultiHeadAttention(units, heads, dropout=dropout,
                                                 cross_attention=True,
                                                 prefix="crossattn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln3 = nn.LayerNorm(prefix="ln3_")

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        x = self.ln1(x + self.self_attn(x))
        x = self.ln2(x + self.cross_attn(x, memory, mem_mask))
        return self.ln3(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6, num_heads=8,
                 dropout=0.1, max_length=512, **kw):
        super().__init__(**kw)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                layer = _EncoderLayer(units, hidden_size, num_heads, dropout,
                                      prefix=f"layer{i}_")
                self.register_child(layer, f"layer{i}")
                self.layers.append(layer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None):
        L = x.shape[1]
        pe = _position_encoding(self._max_length, self._units, x._data.dtype
                                if hasattr(x, "_data") else jnp.float32)
        from ..ndarray import NDArray
        x = x * (self._units ** 0.5) + NDArray(pe[:L][None])
        if self.dropout is not None:
            x = self.dropout(x)
        for layer in self.layers:
            x = layer(x, mask)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6, num_heads=8,
                 dropout=0.1, max_length=512, **kw):
        super().__init__(**kw)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                layer = _DecoderLayer(units, hidden_size, num_heads, dropout,
                                      prefix=f"layer{i}_")
                self.register_child(layer, f"layer{i}")
                self.layers.append(layer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        L = x.shape[1]
        pe = _position_encoding(self._max_length, self._units, jnp.float32)
        from ..ndarray import NDArray
        x = x * (self._units ** 0.5) + NDArray(pe[:L][None])
        if self.dropout is not None:
            x = self.dropout(x)
        for layer in self.layers:
            x = layer(x, memory, mem_mask)
        return x


class NMTModel(HybridBlock):
    """Encoder-decoder with tied target embedding/output projection.

    ``forward(src, tgt, src_valid_length=None)`` → (B, Lt, vocab_tgt) logits
    (teacher forcing; shift/teacher inputs are the caller's concern, matching
    GluonNLP's training loop).
    """

    def __init__(self, src_vocab: int, tgt_vocab: int, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, dropout=0.1,
                 max_length=512, tie_weights=True, **kw):
        super().__init__(**kw)
        self._units = units
        self._tgt_vocab = tgt_vocab
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units, prefix="src_embed_")
            self.tgt_embed = nn.Embedding(tgt_vocab, units, prefix="tgt_embed_")
            self.encoder = TransformerEncoder(units, hidden_size, num_layers,
                                              num_heads, dropout, max_length,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(units, hidden_size, num_layers,
                                              num_heads, dropout, max_length,
                                              prefix="dec_")
            if tie_weights:
                self.proj_weight = self.tgt_embed.weight
            else:
                self.proj_weight = self.params.get(
                    "proj_weight", shape=(tgt_vocab, units))
            self.proj_bias = self.params.get("proj_bias", shape=(tgt_vocab,),
                                             init="zeros")

    def _src_mask(self, F, src_valid_length, B, L):
        if src_valid_length is None:
            return None
        steps = F.arange(0, L, dtype="float32").reshape((1, L))
        m = F.broadcast_lesser(steps, src_valid_length.reshape((B, 1)))
        return m.reshape((B, 1, 1, L))

    def encode(self, src, src_valid_length=None):
        from .. import ndarray as F
        B, L = src.shape
        mask = self._src_mask(F, src_valid_length, B, L)
        return self.encoder(self.src_embed(src), mask), mask

    def hybrid_forward(self, F, src, tgt, src_valid_length=None,
                       proj_weight=None, proj_bias=None):
        B, Ls = src.shape[0], src.shape[1]
        mask = self._src_mask(F, src_valid_length, B, Ls)
        memory = self.encoder(self.src_embed(src), mask)
        out = self.decoder(self.tgt_embed(tgt), memory, mask)
        return F.FullyConnected(out, proj_weight, proj_bias,
                                num_hidden=self._tgt_vocab, flatten=False)


def transformer_sharding_rules(extra=()):
    from ..parallel.sharding import P, ShardingRules
    return ShardingRules(list(extra) + [
        (r".*(qkv|query|kv)_weight", P("tp", None)),
        (r".*(qkv|query|kv)_bias", P("tp")),
        (r".*(proj|ffn2)_weight", P(None, "tp")),
        (r".*ffn1_weight", P("tp", None)),
        (r".*ffn1_bias", P("tp")),
        (r".*embed_weight", P("tp", None)),
    ])


# ---------------------------------------------------------------------------
# incremental (KV-cached) decode path — the serve/decode engine's model math
# ---------------------------------------------------------------------------

_LN_EPS = 1e-5          # matches nn.LayerNorm's default epsilon


def _ln(x, g, b):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + _LN_EPS) * g + b


def incremental_decode_params(model: NMTModel):
    """Extract the decoder-side parameters of an :class:`NMTModel` as a
    plain jnp pytree — the argument layout :func:`nmt_step` /
    :func:`nmt_paged_step` consume. Re-extract after a weight sync
    (cheap: the arrays are shared, not copied)."""
    def d(p):
        return p.data()._data

    layers = []
    for layer in model.decoder.layers:
        layers.append({
            "qkv_w": d(layer.self_attn.qkv.weight),
            "qkv_b": d(layer.self_attn.qkv.bias),
            "sproj_w": d(layer.self_attn.proj.weight),
            "sproj_b": d(layer.self_attn.proj.bias),
            "q_w": d(layer.cross_attn.q_proj.weight),
            "q_b": d(layer.cross_attn.q_proj.bias),
            "kv_w": d(layer.cross_attn.kv_proj.weight),
            "kv_b": d(layer.cross_attn.kv_proj.bias),
            "cproj_w": d(layer.cross_attn.proj.weight),
            "cproj_b": d(layer.cross_attn.proj.bias),
            "ln1_g": d(layer.ln1.gamma), "ln1_b": d(layer.ln1.beta),
            "ln2_g": d(layer.ln2.gamma), "ln2_b": d(layer.ln2.beta),
            "ln3_g": d(layer.ln3.gamma), "ln3_b": d(layer.ln3.beta),
            "ffn1_w": d(layer.ffn.ffn1.weight),
            "ffn1_b": d(layer.ffn.ffn1.bias),
            "ffn2_w": d(layer.ffn.ffn2.weight),
            "ffn2_b": d(layer.ffn.ffn2.bias),
        })
    return {"embed": d(model.tgt_embed.weight),
            "proj_w": d(model.proj_weight), "proj_b": d(model.proj_bias),
            "pe": _position_encoding(model.decoder._max_length,
                                     model._units),
            "layers": layers}


def cross_attention_kv(params, memory):
    """Per-layer cross-attention K/V from encoder memory ``(B, Ls, U)`` —
    the compute the prefill graph amortizes: ``(NL, B, Ls, 2U)``."""
    return jnp.stack([memory @ p["kv_w"].T + p["kv_b"]
                      for p in params["layers"]])


def _attend(q, keys, vals, mask, num_heads):
    """Single-query attention: q (B, U), keys/vals (B, T, U), mask (B, T)
    with 1 = attend → (B, U)."""
    B, T, U = keys.shape
    H, dh = num_heads, U // num_heads
    qh = q.reshape(B, H, dh)
    kh = keys.reshape(B, T, H, dh)
    vh = vals.reshape(B, T, H, dh)
    s = jnp.einsum("bhd,bthd->bht", qh, kh,
                   preferred_element_type=jnp.float32) * (dh ** -0.5)
    s = jnp.where(mask[:, None, :], s, -1e9)
    a = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bthd->bhd", a, vh).reshape(B, U)


def _step_body(params, num_heads, tokens, positions, cross_kv, mem_mask,
               self_kv_of, write_kv):
    """Shared single-token decoder step; the contiguous and paged variants
    differ only in how self-attention K/V are stored (``write_kv``) and
    read back (``self_kv_of``)."""
    U = params["embed"].shape[1]
    x = params["embed"][tokens] * (U ** 0.5) + params["pe"][positions]
    for li, p in enumerate(params["layers"]):
        qkv = x @ p["qkv_w"].T + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        write_kv(li, k, v)
        keys, vals, smask = self_kv_of(li)
        attn = _attend(q, keys, vals, smask, num_heads)
        x = _ln(x + (attn @ p["sproj_w"].T + p["sproj_b"]),
                p["ln1_g"], p["ln1_b"])
        cq = x @ p["q_w"].T + p["q_b"]
        ck, cv = jnp.split(cross_kv[li], 2, axis=-1)
        cmask = (jnp.ones(ck.shape[:2], bool) if mem_mask is None
                 else mem_mask)
        cattn = _attend(cq, ck, cv, cmask, num_heads)
        x = _ln(x + (cattn @ p["cproj_w"].T + p["cproj_b"]),
                p["ln2_g"], p["ln2_b"])
        h = jax.nn.relu(x @ p["ffn1_w"].T + p["ffn1_b"])
        x = _ln(x + (h @ p["ffn2_w"].T + p["ffn2_b"]),
                p["ln3_g"], p["ln3_b"])
    return x @ params["proj_w"].T + params["proj_b"]


def nmt_step(params, num_heads, cache_k, cache_v, cross_kv, mem_mask,
             tokens, positions):
    """One incremental decoder step over a **contiguous** KV cache.

    ``cache_k``/``cache_v``: (NL, B, T, U); ``cross_kv``: (NL, B, Ls, 2U);
    ``mem_mask``: (B, Ls) 1 = attend, or None; ``tokens``/``positions``:
    (B,) int32 (per-row positions, so a continuous batch can hold
    sequences of different lengths). Returns (logits (B, V), cache_k,
    cache_v) — fixed shapes, so the jitted step compiles exactly once.
    """
    T = cache_k.shape[2]
    smask = jnp.arange(T)[None, :] <= positions[:, None]
    state = {"k": cache_k, "v": cache_v}

    def write(li, k, v):
        upd = jax.vmap(lambda c, row, t:
                       jax.lax.dynamic_update_slice(c, row[None], (t, 0)))
        state["k"] = state["k"].at[li].set(upd(state["k"][li], k, positions))
        state["v"] = state["v"].at[li].set(upd(state["v"][li], v, positions))

    def read(li):
        return state["k"][li], state["v"][li], smask

    logits = _step_body(params, num_heads, tokens, positions, cross_kv,
                        mem_mask, read, write)
    return logits, state["k"], state["v"]


def nmt_paged_step(params, num_heads, block_size, pool_k, pool_v,
                   block_tables, positions, tokens, cross_kv, mem_mask):
    """One incremental decoder step over a **paged** KV cache.

    ``pool_k``/``pool_v``: (NB, NL, block_size, U) — the per-replica block
    pool shared by every in-flight sequence; ``block_tables``: (B, nb)
    int32 rows of physical block ids (the per-sequence page table);
    ``positions``/``tokens``: (B,) int32. Each step writes this token's
    K/V into page ``block_tables[i, pos // block_size]`` slot
    ``pos % block_size`` and attends over the gathered pages ≤ pos.
    Returns (logits, pool_k, pool_v) — fixed shapes regardless of how
    ragged the in-flight generation lengths are.
    """
    B, nb = block_tables.shape
    T = nb * block_size
    blk = jnp.take_along_axis(block_tables,
                              (positions[:, None] // block_size), axis=1)[:, 0]
    slot = positions % block_size
    smask = jnp.arange(T)[None, :] <= positions[:, None]
    state = {"k": pool_k, "v": pool_v}

    def write(li, k, v):
        state["k"] = state["k"].at[blk, li, slot].set(k)
        state["v"] = state["v"].at[blk, li, slot].set(v)

    def read(li):
        U = params["embed"].shape[1]
        keys = state["k"][block_tables, li].reshape(B, T, U)
        vals = state["v"][block_tables, li].reshape(B, T, U)
        return keys, vals, smask

    logits = _step_body(params, num_heads, tokens, positions, cross_kv,
                        mem_mask, read, write)
    return logits, state["k"], state["v"]


_nmt_step_jit = jax.jit(nmt_step, static_argnums=(1,))


def beam_search(model: NMTModel, src, src_valid_length=None, beam_size: int = 4,
                max_length: int = 32, bos_id: int = 1, eos_id: int = 2,
                alpha: float = 0.6):
    """Beam search on the incremental (KV-cached) decode path.

    Encodes once, precomputes the per-layer cross-attention K/V once,
    then runs ``max_length`` single-token :func:`nmt_step` calls — O(L)
    decoder compute instead of the reference loop's O(L²) full re-decode
    per emitted token. Every step has the same fixed shapes, so the step
    compiles exactly once; beam reordering is a cache-row gather. Output
    parity with :func:`beam_search_reference` (the old full-re-decode
    loop) is pinned by a seeded test.
    Returns (sequences (B, beam, max_length), scores (B, beam)).
    """
    from ..ndarray import NDArray
    from .. import autograd

    src_nd = src if isinstance(src, NDArray) else NDArray(jnp.asarray(src))
    B = src_nd.shape[0]
    K = beam_size
    vl_nd = src_valid_length if isinstance(src_valid_length, NDArray) or \
        src_valid_length is None else NDArray(jnp.asarray(src_valid_length))
    with autograd.predict_mode():
        memory, mask = model.encode(src_nd, vl_nd)
    try:
        params = incremental_decode_params(model)
    except Exception:
        # decoder params can still be deferred (encode only initializes
        # the encoder side) — one full forward materializes them
        with autograd.predict_mode():
            model(src_nd, NDArray(jnp.full((B, 1), bos_id, jnp.int32)), vl_nd)
        params = incremental_decode_params(model)
    mem = jnp.repeat(memory._data, K, axis=0)            # (B*K, Ls, C)
    cross_kv = cross_attention_kv(params, mem)           # (NL, B*K, Ls, 2U)
    mmask = None if mask is None else \
        jnp.repeat(mask._data[:, 0, 0, :] > 0, K, axis=0)  # (B*K, Ls)

    NL = len(params["layers"])
    U = model._units
    H = model.decoder.layers[0].self_attn._num_heads
    BK = B * K
    cache_k = jnp.zeros((NL, BK, max_length, U), cross_kv.dtype)
    cache_v = jnp.zeros_like(cache_k)

    seqs = jnp.full((BK, max_length + 1), eos_id, jnp.int32)
    seqs = seqs.at[:, 0].set(bos_id)
    scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (K - 1)), B)  # (B*K,)
    done = jnp.zeros((BK,), bool)

    V = model._tgt_vocab
    for t in range(max_length):
        logits, cache_k, cache_v = _nmt_step_jit(
            params, H, cache_k, cache_v, cross_kv, mmask,
            seqs[:, t], jnp.full((BK,), t, jnp.int32))
        logp = jax.nn.log_softmax(logits, -1)
        # finished beams only extend with eos at no cost
        eos_only = jnp.full((V,), -1e9).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None], logp)
        cand = scores[:, None] + logp                    # (B*K, V)
        cand = cand.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(cand, K)     # (B, K)
        beam_idx = top_idx // V + jnp.arange(B)[:, None] * K
        bidx = beam_idx.reshape(-1)
        tok = (top_idx % V).reshape(-1)
        seqs = seqs[bidx]
        seqs = seqs.at[:, t + 1].set(tok)
        # adopting a sibling beam's prefix = adopting its cache rows
        cache_k = cache_k[:, bidx]
        cache_v = cache_v[:, bidx]
        done = done[bidx] | (tok == eos_id)
        scores = top_scores.reshape(-1)

    # length-normalized scores (GNMT alpha rule, as in GluonNLP)
    lengths = jnp.sum((seqs[:, 1:] != eos_id).astype(jnp.float32), -1) + 1.0
    lp = ((5.0 + lengths) / 6.0) ** alpha
    final = (scores / lp).reshape(B, K)
    order = jnp.argsort(-final, axis=-1)
    seqs = seqs.reshape(B, K, -1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    return seqs[:, :, 1:], final


def beam_search_reference(model: NMTModel, src, src_valid_length=None,
                          beam_size: int = 4, max_length: int = 32,
                          bos_id: int = 1, eos_id: int = 2,
                          alpha: float = 0.6):
    """The pre-KV-cache beam search (reference: GluonNLP BeamSearchSampler).

    Encodes once, then decodes ``max_length`` steps. Every step feeds the
    decoder the SAME fixed (B·beam, max_length) token buffer — causal
    masking makes position t depend only on tokens ≤ t, so the step logits
    are read at column t and the decoder compiles exactly once (O(L²) total
    compute). Kept as the parity oracle for :func:`beam_search`.
    Returns (sequences (B, beam, max_length), scores (B, beam)).
    """
    from ..ndarray import NDArray
    from .. import autograd

    src_nd = src if isinstance(src, NDArray) else NDArray(jnp.asarray(src))
    B = src_nd.shape[0]
    K = beam_size
    with autograd.predict_mode():
        memory, mask = model.encode(src_nd, src_valid_length if
                                    isinstance(src_valid_length, NDArray) or
                                    src_valid_length is None
                                    else NDArray(jnp.asarray(src_valid_length)))
    mem = jnp.repeat(memory._data, K, axis=0)            # (B*K, Ls, C)
    mmask = None if mask is None else jnp.repeat(mask._data, K, axis=0)

    seqs = jnp.full((B * K, max_length + 1), eos_id, jnp.int32)
    seqs = seqs.at[:, 0].set(bos_id)
    scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (K - 1)), B)  # (B*K,)
    done = jnp.zeros((B * K,), bool)

    def dec_step(seqs_prefix):
        with autograd.predict_mode():
            out = model.decoder(model.tgt_embed(NDArray(seqs_prefix)),
                                NDArray(mem),
                                None if mmask is None else NDArray(mmask))
            from .. import ndarray as F
            logits = F.FullyConnected(
                out, model.proj_weight.data(), model.proj_bias.data(),
                num_hidden=model._tgt_vocab, flatten=False)
        return logits._data

    V = model._tgt_vocab
    for t in range(max_length):
        # fixed-shape prefix: causality makes column t ignore columns > t
        logits = dec_step(seqs[:, :max_length])[:, t]    # (B*K, V)
        logp = jax.nn.log_softmax(logits, -1)
        # finished beams only extend with eos at no cost
        eos_only = jnp.full((V,), -1e9).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None], logp)
        cand = scores[:, None] + logp                    # (B*K, V)
        cand = cand.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(cand, K)     # (B, K)
        beam_idx = top_idx // V + jnp.arange(B)[:, None] * K
        tok = (top_idx % V).reshape(-1)
        seqs = seqs[beam_idx.reshape(-1)]
        seqs = seqs.at[:, t + 1].set(tok)
        done = done[beam_idx.reshape(-1)] | (tok == eos_id)
        scores = top_scores.reshape(-1)

    # length-normalized scores (GNMT alpha rule, as in GluonNLP)
    lengths = jnp.sum((seqs[:, 1:] != eos_id).astype(jnp.float32), -1) + 1.0
    lp = ((5.0 + lengths) / 6.0) ** alpha
    final = (scores / lp).reshape(B, K)
    order = jnp.argsort(-final, axis=-1)
    seqs = seqs.reshape(B, K, -1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    return seqs[:, :, 1:], final

"""Transformer NMT (encoder-decoder) with beam-search inference.

Reference parity: GluonNLP ``scripts/machine_translation`` /
``gluonnlp/model/transformer.py`` (Transformer-big WMT14 in BASELINE.json)
and the ``BeamSearchSampler`` inference path — SURVEY §2.9.

TPU-native design: training is teacher-forced full-sequence (one MXU-heavy
pass, causal flash attention); beam search decodes with a **static-shape
loop** (``lax.while_loop`` over max_length with a fixed beam) instead of the
reference's dynamic-length Python loop, so the whole decode jit-compiles.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..gluon.block import HybridBlock
from ..gluon import nn
from .transformer import MultiHeadAttention, PositionwiseFFN

__all__ = ["TransformerEncoder", "TransformerDecoder", "NMTModel",
           "beam_search", "transformer_sharding_rules"]


import functools


@functools.lru_cache(maxsize=16)
def _position_encoding(L, C, dtype=jnp.float32):
    # cached: rebuilt tables would otherwise cost a host round-trip on every
    # forward (beam search calls the decoder max_length times)
    pos = onp.arange(L)[:, None]
    dim = onp.arange(C // 2)[None, :]
    angle = pos / onp.power(10000.0, 2 * dim / C)
    out = onp.zeros((L, C), "float32")
    out[:, 0::2] = onp.sin(angle)
    out[:, 1::2] = onp.cos(angle)
    return jnp.asarray(out, dtype)


class _EncoderLayer(HybridBlock):
    def __init__(self, units, hidden, heads, dropout, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.attn = MultiHeadAttention(units, heads, dropout=dropout,
                                           prefix="attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.attn(x, None, mask))
        return self.ln2(x + self.ffn(x))


class _DecoderLayer(HybridBlock):
    def __init__(self, units, hidden, heads, dropout, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.self_attn = MultiHeadAttention(units, heads, dropout=dropout,
                                                causal=True, prefix="selfattn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.cross_attn = MultiHeadAttention(units, heads, dropout=dropout,
                                                 cross_attention=True,
                                                 prefix="crossattn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln3 = nn.LayerNorm(prefix="ln3_")

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        x = self.ln1(x + self.self_attn(x))
        x = self.ln2(x + self.cross_attn(x, memory, mem_mask))
        return self.ln3(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6, num_heads=8,
                 dropout=0.1, max_length=512, **kw):
        super().__init__(**kw)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                layer = _EncoderLayer(units, hidden_size, num_heads, dropout,
                                      prefix=f"layer{i}_")
                self.register_child(layer, f"layer{i}")
                self.layers.append(layer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None):
        L = x.shape[1]
        pe = _position_encoding(self._max_length, self._units, x._data.dtype
                                if hasattr(x, "_data") else jnp.float32)
        from ..ndarray import NDArray
        x = x * (self._units ** 0.5) + NDArray(pe[:L][None])
        if self.dropout is not None:
            x = self.dropout(x)
        for layer in self.layers:
            x = layer(x, mask)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6, num_heads=8,
                 dropout=0.1, max_length=512, **kw):
        super().__init__(**kw)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                layer = _DecoderLayer(units, hidden_size, num_heads, dropout,
                                      prefix=f"layer{i}_")
                self.register_child(layer, f"layer{i}")
                self.layers.append(layer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        L = x.shape[1]
        pe = _position_encoding(self._max_length, self._units, jnp.float32)
        from ..ndarray import NDArray
        x = x * (self._units ** 0.5) + NDArray(pe[:L][None])
        if self.dropout is not None:
            x = self.dropout(x)
        for layer in self.layers:
            x = layer(x, memory, mem_mask)
        return x


class NMTModel(HybridBlock):
    """Encoder-decoder with tied target embedding/output projection.

    ``forward(src, tgt, src_valid_length=None)`` → (B, Lt, vocab_tgt) logits
    (teacher forcing; shift/teacher inputs are the caller's concern, matching
    GluonNLP's training loop).
    """

    def __init__(self, src_vocab: int, tgt_vocab: int, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, dropout=0.1,
                 max_length=512, tie_weights=True, **kw):
        super().__init__(**kw)
        self._units = units
        self._tgt_vocab = tgt_vocab
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units, prefix="src_embed_")
            self.tgt_embed = nn.Embedding(tgt_vocab, units, prefix="tgt_embed_")
            self.encoder = TransformerEncoder(units, hidden_size, num_layers,
                                              num_heads, dropout, max_length,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(units, hidden_size, num_layers,
                                              num_heads, dropout, max_length,
                                              prefix="dec_")
            if tie_weights:
                self.proj_weight = self.tgt_embed.weight
            else:
                self.proj_weight = self.params.get(
                    "proj_weight", shape=(tgt_vocab, units))
            self.proj_bias = self.params.get("proj_bias", shape=(tgt_vocab,),
                                             init="zeros")

    def _src_mask(self, F, src_valid_length, B, L):
        if src_valid_length is None:
            return None
        steps = F.arange(0, L, dtype="float32").reshape((1, L))
        m = F.broadcast_lesser(steps, src_valid_length.reshape((B, 1)))
        return m.reshape((B, 1, 1, L))

    def encode(self, src, src_valid_length=None):
        from .. import ndarray as F
        B, L = src.shape
        mask = self._src_mask(F, src_valid_length, B, L)
        return self.encoder(self.src_embed(src), mask), mask

    def hybrid_forward(self, F, src, tgt, src_valid_length=None,
                       proj_weight=None, proj_bias=None):
        B, Ls = src.shape[0], src.shape[1]
        mask = self._src_mask(F, src_valid_length, B, Ls)
        memory = self.encoder(self.src_embed(src), mask)
        out = self.decoder(self.tgt_embed(tgt), memory, mask)
        return F.FullyConnected(out, proj_weight, proj_bias,
                                num_hidden=self._tgt_vocab, flatten=False)


def transformer_sharding_rules(extra=()):
    from ..parallel.sharding import P, ShardingRules
    return ShardingRules(list(extra) + [
        (r".*(qkv|query|kv)_weight", P("tp", None)),
        (r".*(qkv|query|kv)_bias", P("tp")),
        (r".*(proj|ffn2)_weight", P(None, "tp")),
        (r".*ffn1_weight", P("tp", None)),
        (r".*ffn1_bias", P("tp")),
        (r".*embed_weight", P("tp", None)),
    ])


def beam_search(model: NMTModel, src, src_valid_length=None, beam_size: int = 4,
                max_length: int = 32, bos_id: int = 1, eos_id: int = 2,
                alpha: float = 0.6):
    """Static-shape beam search (reference: GluonNLP BeamSearchSampler).

    Encodes once, then decodes ``max_length`` steps. Every step feeds the
    decoder the SAME fixed (B·beam, max_length) token buffer — causal
    masking makes position t depend only on tokens ≤ t, so the step logits
    are read at column t and the decoder compiles exactly once (O(L²) total
    compute; incremental KV caching is a later kernel-level optimization).
    Returns (sequences (B, beam, max_length), scores (B, beam)).
    """
    from ..ndarray import NDArray
    from .. import autograd

    src_nd = src if isinstance(src, NDArray) else NDArray(jnp.asarray(src))
    B = src_nd.shape[0]
    K = beam_size
    with autograd.predict_mode():
        memory, mask = model.encode(src_nd, src_valid_length if
                                    isinstance(src_valid_length, NDArray) or
                                    src_valid_length is None
                                    else NDArray(jnp.asarray(src_valid_length)))
    mem = jnp.repeat(memory._data, K, axis=0)            # (B*K, Ls, C)
    mmask = None if mask is None else jnp.repeat(mask._data, K, axis=0)

    seqs = jnp.full((B * K, max_length + 1), eos_id, jnp.int32)
    seqs = seqs.at[:, 0].set(bos_id)
    scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (K - 1)), B)  # (B*K,)
    done = jnp.zeros((B * K,), bool)

    def dec_step(seqs_prefix):
        with autograd.predict_mode():
            out = model.decoder(model.tgt_embed(NDArray(seqs_prefix)),
                                NDArray(mem),
                                None if mmask is None else NDArray(mmask))
            from .. import ndarray as F
            logits = F.FullyConnected(
                out, model.proj_weight.data(), model.proj_bias.data(),
                num_hidden=model._tgt_vocab, flatten=False)
        return logits._data

    V = model._tgt_vocab
    for t in range(max_length):
        # fixed-shape prefix: causality makes column t ignore columns > t
        logits = dec_step(seqs[:, :max_length])[:, t]    # (B*K, V)
        logp = jax.nn.log_softmax(logits, -1)
        # finished beams only extend with eos at no cost
        eos_only = jnp.full((V,), -1e9).at[eos_id].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None], logp)
        cand = scores[:, None] + logp                    # (B*K, V)
        cand = cand.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(cand, K)     # (B, K)
        beam_idx = top_idx // V + jnp.arange(B)[:, None] * K
        tok = (top_idx % V).reshape(-1)
        seqs = seqs[beam_idx.reshape(-1)]
        seqs = seqs.at[:, t + 1].set(tok)
        done = done[beam_idx.reshape(-1)] | (tok == eos_id)
        scores = top_scores.reshape(-1)

    # length-normalized scores (GNMT alpha rule, as in GluonNLP)
    lengths = jnp.sum((seqs[:, 1:] != eos_id).astype(jnp.float32), -1) + 1.0
    lp = ((5.0 + lengths) / 6.0) ** alpha
    final = (scores / lp).reshape(B, K)
    order = jnp.argsort(-final, axis=-1)
    seqs = seqs.reshape(B, K, -1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    return seqs[:, :, 1:], final

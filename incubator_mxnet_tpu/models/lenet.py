"""LeNet-5 — the train_mnist.py smoke model.

Reference parity: ``example/image-classification/train_mnist.py`` +
``symbols/lenet.py`` (SURVEY §2.9 / §7 stage 4: the first end-to-end
milestone). Both API styles ship: :class:`LeNet` (Gluon HybridBlock) and
:func:`lenet_symbol` (Module-era symbol ending in SoftmaxOutput).
"""
from __future__ import annotations

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["LeNet", "lenet", "lenet_symbol", "mlp_symbol"]


class LeNet(HybridBlock):
    def __init__(self, classes: int = 10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(20, kernel_size=5, activation="tanh"))
            self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Conv2D(50, kernel_size=5, activation="tanh"))
            self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(500, activation="tanh"))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def lenet(**kwargs) -> LeNet:
    return LeNet(**kwargs)


def lenet_symbol(classes: int = 10):
    """Module-era LeNet (reference: example/.../symbols/lenet.py)."""
    from .. import symbol as sym
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    t1 = sym.Activation(c1, act_type="tanh", name="tanh1")
    p1 = sym.Pooling(t1, pool_type="max", kernel=(2, 2), stride=(2, 2),
                     name="pool1")
    c2 = sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    t2 = sym.Activation(c2, act_type="tanh", name="tanh2")
    p2 = sym.Pooling(t2, pool_type="max", kernel=(2, 2), stride=(2, 2),
                     name="pool2")
    f = sym.flatten(p2, name="flatten")
    fc1 = sym.FullyConnected(f, num_hidden=500, name="fc1")
    t3 = sym.Activation(fc1, act_type="tanh", name="tanh3")
    fc2 = sym.FullyConnected(t3, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"),
                             name="softmax")


def mlp_symbol(classes: int = 10):
    """train_mnist.py's default MLP."""
    from .. import symbol as sym
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(sym.flatten(data, name="flat"), num_hidden=128,
                             name="fc1")
    a1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(a1, num_hidden=64, name="fc2")
    a2 = sym.Activation(fc2, act_type="relu", name="relu2")
    fc3 = sym.FullyConnected(a2, num_hidden=classes, name="fc3")
    return sym.SoftmaxOutput(fc3, sym.Variable("softmax_label"),
                             name="softmax")

"""BERT — the flagship (north-star) model family.

Reference parity: GluonNLP ``scripts/bert/`` + ``gluonnlp/model/bert.py``
(BERTEncoder, BERTModel with use_pooler/use_decoder/use_classifier), running
on the contrib interleaved-MHA ops (SURVEY §2.9: the BASELINE.json north-star
workload). Same forward contract as GluonNLP:

    seq, pooled, nsp, mlm = model(ids, token_types, valid_length, positions)

TPU-native design: the whole pretraining step — embeddings, N encoder layers
on flash attention, both heads, loss, grads, AdamW/LAMB update — compiles to
ONE XLA executable via ``parallel.ShardedTrainer`` with
:func:`bert_sharding_rules` (Megatron-style TP over the ``tp`` mesh axis,
batch over ``dp``, sequence over ``sp``); bf16 activations via ``dtype``.
"""
from __future__ import annotations

from typing import Optional

from ..gluon.block import HybridBlock
from ..gluon import nn, loss as loss_mod
from .transformer import TransformerEncoderCell

__all__ = ["BERTEncoder", "BERTModel", "bert_sharding_rules", "get_bert",
           "bert_pretrain_loss", "BERT_CONFIGS"]

#: GluonNLP model-name convention: bert_<layers>_<units>_<heads>
BERT_CONFIGS = {
    "bert_2_128_2": dict(num_layers=2, units=128, hidden_size=512,
                         num_heads=2),          # tiny (tests)
    "bert_12_768_12": dict(num_layers=12, units=768, hidden_size=3072,
                           num_heads=12),       # base
    "bert_24_1024_16": dict(num_layers=24, units=1024, hidden_size=4096,
                            num_heads=16),      # large
}


class BERTEncoder(HybridBlock):
    """Stack of post-LN transformer encoder cells.

    ``remat=True`` wraps each cell in ``jax.checkpoint`` when the stack is
    compiled (hybridize / ShardedTrainer): activations inside a layer are
    rematerialized in backward instead of living in HBM across the whole
    stack — O(L·C·1) live activations instead of O(L·C·layers), the lever
    that lets BERT-large batches fill the chip (SURVEY §7 "jax.checkpoint /
    rematerialisation"). No effect on eager execution.
    """

    def __init__(self, num_layers: int, units: int, hidden_size: int,
                 num_heads: int, dropout: float = 0.1, dtype="float32",
                 weight_initializer=None, remat: bool = False, **kwargs):
        super().__init__(**kwargs)
        self._num_layers = num_layers
        self._remat = remat
        self._dropout = dropout
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                cell = TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    dtype=dtype, prefix=f"layer{i}_",
                    weight_initializer=weight_initializer)
                self.register_child(cell, f"layer{i}")
                self.layers.append(cell)

    def hybrid_forward(self, F, x, mask=None):
        from ..gluon.block import _is_tracing
        import jax
        # checkpoint only under a REAL jit trace: the ShardedTrainer warmup
        # runs eagerly with the tracing flag set (to finish deferred init),
        # and an eager jax.checkpoint would trace deferred param init into
        # its region — the init value would then be a region-local tracer
        # stored on the Parameter (UnexpectedTracerError on reuse).
        if self._remat and _is_tracing() \
                and isinstance(x._data, jax.core.Tracer):
            from .. import random as random_mod
            from ..ndarray import NDArray
            need_rng = self._dropout > 0
            for cell in self.layers:
                # jax.checkpoint over the cell body; params/mask are
                # closed-over tracers (new-style remat closure-converts
                # them, cotangents flow). RNG must NOT be stateful across
                # the checkpoint boundary: a next_key() split inside the
                # region would store a region-local tracer in the ambient
                # trace_rng (UnexpectedTracerError). Instead draw one key
                # per layer at the outer trace level and thread it in as a
                # checkpoint ARGUMENT — backward's recompute then replays
                # the exact same dropout masks by construction.
                if need_rng:
                    layer_key = random_mod.next_key()

                    def body(xv, kv, cell=cell, mask=mask, ctx=x.context):
                        with random_mod.trace_rng(kv):
                            return cell(NDArray(xv, ctx=ctx), mask)._data

                    x = NDArray(jax.checkpoint(body)(x._data, layer_key),
                                ctx=x.context)
                else:
                    def body(xv, cell=cell, mask=mask, ctx=x.context):
                        return cell(NDArray(xv, ctx=ctx), mask)._data

                    x = NDArray(jax.checkpoint(body)(x._data), ctx=x.context)
            return x
        for cell in self.layers:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT with optional pooler (NSP input), MLM decoder and NSP classifier.

    ``forward(inputs, token_types, valid_length=None, masked_positions=None)``
    returns, depending on the ``use_*`` flags (GluonNLP contract):
    ``seq_out`` | ``(seq_out, pooled)`` | ``(seq_out, pooled, nsp)`` |
    ``(seq_out, pooled, nsp, mlm)``.
    """

    def __init__(self, vocab_size: int, units: int = 768,
                 hidden_size: int = 3072, num_layers: int = 12,
                 num_heads: int = 12, max_length: int = 512,
                 token_type_vocab_size: int = 2, dropout: float = 0.1,
                 use_pooler: bool = True, use_decoder: bool = True,
                 use_classifier: bool = True, dtype="float32",
                 embed_initializer=None, remat: bool = False, **kwargs):
        super().__init__(**kwargs)
        self._vocab_size = vocab_size
        self._units = units
        self._max_length = max_length
        if use_classifier and not use_pooler:
            raise ValueError("use_classifier=True requires use_pooler=True "
                             "(the NSP head reads the pooled [CLS] vector)")
        self.use_pooler = use_pooler
        self.use_decoder = use_decoder
        self.use_classifier = use_classifier
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units, dtype=dtype,
                                           prefix="word_embed_",
                                           weight_initializer=embed_initializer)
            self.token_type_embed = nn.Embedding(
                token_type_vocab_size, units, dtype=dtype,
                prefix="token_type_embed_", weight_initializer=embed_initializer)
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units), dtype=dtype,
                init=embed_initializer)
            self.embed_ln = nn.LayerNorm(epsilon=1e-12, in_channels=units,
                                         prefix="embed_ln_")
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout=dropout, dtype=dtype,
                                       prefix="encoder_", remat=remat)
            if use_pooler:
                self.pooler = nn.Dense(units, flatten=False, in_units=units,
                                       activation="tanh", prefix="pooler_",
                                       dtype=dtype)
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False, in_units=units,
                                           prefix="nsp_", dtype=dtype)
            if use_decoder:
                self.decoder_transform = nn.Dense(
                    units, flatten=False, in_units=units, activation="gelu",
                    prefix="decoder_transform_", dtype=dtype)
                self.decoder_ln = nn.LayerNorm(epsilon=1e-12, in_channels=units,
                                               prefix="decoder_ln_")
                # Output projection is TIED to the word embedding (reference:
                # GluonNLP BERTModel._decode shares word_embed params).
                self.decoder_tied_weight = self.word_embed.weight
                self.decoder_bias = self.params.get(
                    "decoder_bias", shape=(vocab_size,), init="zeros",
                    dtype=dtype)

    # -- helpers -----------------------------------------------------------
    def _attn_mask(self, F, valid_length, B, L):
        if valid_length is None:
            return None
        steps = F.arange(0, L, dtype="float32").reshape((1, L))
        mask = F.broadcast_lesser(steps, valid_length.reshape((B, 1)))
        return mask.reshape((B, 1, 1, L))

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None,
                       masked_positions=None, position_weight=None,
                       decoder_tied_weight=None, decoder_bias=None):
        B, L = inputs.shape[0], inputs.shape[1]
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        pos = F.slice_axis(position_weight, axis=0, begin=0, end=L)
        x = x + pos.reshape((1, L, self._units))
        x = self.embed_ln(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        mask = self._attn_mask(F, valid_length, B, L)
        seq = self.encoder(x, mask)
        outputs = [seq]
        pooled = None
        if self.use_pooler:
            cls = F.slice_axis(seq, axis=1, begin=0, end=1).reshape(
                (B, self._units))
            pooled = self.pooler(cls)
            outputs.append(pooled)
        if self.use_classifier:
            outputs.append(self.classifier(pooled))
        if self.use_decoder and masked_positions is not None:
            P = masked_positions.shape[1]
            flat = seq.reshape((B * L, self._units))
            offsets = F.arange(0, B, dtype="int32").reshape((B, 1)) * L
            idx = (masked_positions.astype("int32") + offsets).reshape((B * P,))
            h = F.take(flat, idx, axis=0).reshape((B, P, self._units))
            h = self.decoder_ln(self.decoder_transform(h))
            mlm = F.FullyConnected(h, decoder_tied_weight, decoder_bias,
                                   num_hidden=self._vocab_size, flatten=False)
            outputs.append(mlm)
        return tuple(outputs) if len(outputs) > 1 else outputs[0]


def get_bert(name_or_cfg="bert_12_768_12", vocab_size: int = 30522,
             max_length: int = 512, dropout: float = 0.1, dtype="float32",
             **overrides) -> BERTModel:
    """Model-zoo constructor (reference: gluonnlp.model.get_model('bert_...'))."""
    cfg = dict(BERT_CONFIGS[name_or_cfg]) if isinstance(name_or_cfg, str) \
        else dict(name_or_cfg)
    cfg.update(overrides)
    return BERTModel(vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, dtype=dtype, **cfg)


def bert_sharding_rules(extra=()):
    """Megatron-style TP rules for :class:`parallel.ShardedTrainer`.

    Dense weights are (out, in): qkv/ffn1 split the output dim over ``tp``
    (column-parallel), proj/ffn2 split the input dim (row-parallel) so XLA
    inserts exactly one reduce per block; embeddings shard the vocab dim.
    """
    from ..parallel.sharding import P, ShardingRules
    return ShardingRules(list(extra) + [
        (r".*qkv_weight", P("tp", None)),
        (r".*qkv_bias", P("tp")),
        (r".*(proj|ffn2)_weight", P(None, "tp")),
        (r".*ffn1_weight", P("tp", None)),
        (r".*ffn1_bias", P("tp")),
        (r".*word_embed_weight", P("tp", None)),
        (r".*decoder_bias", P("tp")),
    ])


def bert_pretrain_loss(outputs, mlm_labels, mlm_weights, nsp_labels):
    """Combined MLM + NSP loss (reference: scripts/bert/pretraining_utils.py).

    ``outputs`` = BERTModel 4-tuple; ``mlm_labels/mlm_weights`` (B, P) with
    weight 0 on padding positions; ``nsp_labels`` (B,).
    """
    _, _, nsp_scores, mlm_scores = outputs
    ce = loss_mod.SoftmaxCrossEntropyLoss()
    mlm = ce(mlm_scores, mlm_labels, mlm_weights.expand_dims(-1))
    denom = mlm_weights.mean() + 1e-8
    nsp = ce(nsp_scores, nsp_labels)
    return mlm.mean() / denom + nsp.mean()

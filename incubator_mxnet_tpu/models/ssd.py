"""SSD — single-shot detection.

Reference parity: GluonCV ``gluoncv/model_zoo/ssd`` + the in-tree MultiBox
ops (``src/operator/contrib/multibox_*.cc``) exercised by BASELINE.json's
SSD-512 config. Anchors/targets/decode all go through the fixed-shape
``multibox_*`` ops in ``ops/detection.py`` — everything static-shape, so
training and inference both jit.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["SSD", "ssd_300", "SSDTargetLoss"]


class _FeatureExtractor(HybridBlock):
    """Small VGG-style trunk emitting multi-scale maps (GluonCV uses the
    zoo backbones; this trunk keeps tests/dataset-free usage light — swap in
    model_zoo.vision features for the full recipe)."""

    def __init__(self, filters: Sequence[int] = (32, 64, 128, 128, 128), **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.blocks = []
            for i, f in enumerate(filters):
                blk = nn.HybridSequential(prefix=f"scale{i}_")
                with blk.name_scope():
                    blk.add(nn.Conv2D(f, 3, padding=1, activation="relu"))
                    blk.add(nn.Conv2D(f, 3, padding=1, activation="relu"))
                    blk.add(nn.MaxPool2D(2, 2))
                self.register_child(blk, f"scale{i}")
                self.blocks.append(blk)

    def hybrid_forward(self, F, x):
        feats = []
        for blk in self.blocks:
            x = blk(x)
            feats.append(x)
        return tuple(feats[1:])  # skip the stem scale


class SSD(HybridBlock):
    """``forward(x)`` → (cls_preds (B, N, num_cls+1), box_preds (B, N*4),
    anchors (1, N, 4)). Train with :class:`SSDTargetLoss`; decode with
    ``contrib.nd.MultiBoxDetection`` (see ``detect``)."""

    def __init__(self, num_classes: int,
                 sizes: Sequence[Sequence[float]] = ((0.2, 0.27), (0.37, 0.44),
                                                     (0.54, 0.62), (0.71, 0.79)),
                 ratios: Sequence[Sequence[float]] = ((1, 2, 0.5),) * 4,
                 filters: Sequence[int] = (32, 64, 128, 128, 128), **kw):
        super().__init__(**kw)
        self._num_classes = num_classes
        self._sizes = sizes
        self._ratios = ratios
        with self.name_scope():
            self.features = _FeatureExtractor(filters, prefix="features_")
            self.cls_preds = []
            self.box_preds = []
            for i, (s, r) in enumerate(zip(sizes, ratios)):
                a = len(s) + len(r) - 1
                cp = nn.Conv2D(a * (num_classes + 1), 3, padding=1,
                               prefix=f"cls{i}_")
                bp = nn.Conv2D(a * 4, 3, padding=1, prefix=f"box{i}_")
                self.register_child(cp, f"cls{i}")
                self.register_child(bp, f"box{i}")
                self.cls_preds.append(cp)
                self.box_preds.append(bp)

    def hybrid_forward(self, F, x):
        feats = self.features(x)
        B = x.shape[0]
        cls_out, box_out, anchors = [], [], []
        for feat, cp, bp, s, r in zip(feats, self.cls_preds, self.box_preds,
                                      self._sizes, self._ratios):
            c = cp(feat)   # (B, A*(C+1), H, W)
            b = bp(feat)   # (B, A*4, H, W)
            cls_out.append(F.reshape(
                F.transpose(c, axes=(0, 2, 3, 1)),
                (B, -1, self._num_classes + 1)))
            box_out.append(F.reshape(F.transpose(b, axes=(0, 2, 3, 1)),
                                     (B, -1)))
            anchors.append(F.multibox_prior(feat, sizes=tuple(s),
                                            ratios=tuple(r)))
        cls_preds = F.concat(*cls_out, dim=1)
        box_preds = F.concat(*box_out, dim=1)
        anchor = F.concat(*anchors, dim=1)
        return cls_preds, box_preds, anchor

    def detect(self, x, threshold=0.01, nms_threshold=0.45, nms_topk=400):
        """Full inference: forward + decode + NMS → (B, N, 6)."""
        from .. import ndarray as F
        from .. import autograd
        with autograd.predict_mode():
            cls_preds, box_preds, anchor = self(x)
            cls_prob = F.softmax(cls_preds, axis=-1)
            cls_prob = F.transpose(cls_prob, axes=(0, 2, 1))
            return F.multibox_detection(
                cls_prob, box_preds, anchor, threshold=threshold,
                nms_threshold=nms_threshold, nms_topk=nms_topk)


class SSDTargetLoss(HybridBlock):
    """MultiBoxTarget + (CE cls loss, SmoothL1 box loss) — the standard SSD
    training objective (reference: GluonCV SSDMultiBoxLoss over the
    MultiBoxTarget op)."""

    def __init__(self, negative_mining_ratio: float = 3.0, **kw):
        super().__init__(**kw)
        self._ratio = negative_mining_ratio

    def hybrid_forward(self, F, cls_preds, box_preds, anchor, label):
        cls_pred_t = F.transpose(cls_preds, axes=(0, 2, 1))
        loc_t, loc_mask, cls_t = F.multibox_target(
            anchor, label, cls_pred_t,
            negative_mining_ratio=self._ratio, ignore_label=-1.0)
        # anchors marked ignore (-1) by hard negative mining drop out of CE
        keep = F.greater_equal(cls_t, cls_t * 0.0)
        ce = -F.pick(F.log_softmax(cls_preds, axis=-1),
                     F.clip(cls_t, a_min=0.0), axis=-1)
        cls_loss = ce * keep
        num_pos = F.sum(F.greater(cls_t, cls_t * 0.0)) + 1.0
        box_loss = F.smooth_l1((box_preds - loc_t) * loc_mask, scalar=1.0)
        return (F.sum(cls_loss) + F.sum(box_loss)) / num_pos


def ssd_300(num_classes: int = 20, **kw) -> SSD:
    return SSD(num_classes, **kw)

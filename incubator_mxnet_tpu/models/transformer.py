"""Transformer building blocks shared by BERT and the NMT Transformer.

Reference parity: GluonNLP's ``BERTEncoder``/``TransformerEncoderCell``
(gluonnlp/model/bert.py, transformer.py), whose hot path is the contrib
interleaved-MHA ops (``src/operator/contrib/transformer.cc`` — SURVEY §2.4).

TPU-native design: one fused QKV projection (a single MXU matmul over the
batch·seq rows) followed by :func:`~incubator_mxnet_tpu.ops.attention.
dot_product_attention` — which lowers to the Pallas flash kernel on TPU. The
reference's (B·H, L, L) score tensor never exists in HBM.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "StackedTransformerEncoder"]


class MultiHeadAttention(HybridBlock):
    """Multi-head attention with fused QKV projection.

    ``__call__(query, kv, mask)`` — pass ``kv=None`` (or ``query``) for
    self-attention (one fused qkv matmul); a different ``kv`` gives
    cross-attention (q proj + fused kv proj, the encdec layout of the
    reference's ``interleaved_matmul_encdec_*`` ops).

    ``mask`` is broadcastable to (B, H, Lq, Lk), 1 = attend; ``None`` = full.
    """

    def __init__(self, units: int, num_heads: int, dropout: float = 0.0,
                 causal: bool = False, use_bias: bool = True, dtype="float32",
                 cross_attention: bool = False, weight_initializer=None,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._cross = cross_attention
        with self.name_scope():
            # Only the projections this cell actually uses exist — dead
            # parameters would get optimizer state and distort MFU accounting.
            if cross_attention:
                self.q_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                       in_units=units, dtype=dtype,
                                       prefix="query_",
                                       weight_initializer=weight_initializer)
                self.kv_proj = nn.Dense(2 * units, flatten=False,
                                        use_bias=use_bias, in_units=units,
                                        dtype=dtype, prefix="kv_",
                                        weight_initializer=weight_initializer)
            else:
                self.qkv = nn.Dense(3 * units, flatten=False, use_bias=use_bias,
                                    in_units=units, dtype=dtype, prefix="qkv_",
                                    weight_initializer=weight_initializer)
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 in_units=units, dtype=dtype, prefix="proj_",
                                 weight_initializer=weight_initializer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def _heads(self, F, x, n):
        # (B, L, n*C) -> n tensors of (B, H, L, d)
        B, L = x.shape[0], x.shape[1]
        H, d = self._num_heads, self._units // self._num_heads
        parts = F.split(x, num_outputs=n, axis=2) if n > 1 else [x]
        outs = []
        for p in parts:
            outs.append(F.transpose(F.reshape(p, (B, L, H, d)), axes=(0, 2, 1, 3)))
        return outs

    def hybrid_forward(self, F, query, kv=None, mask=None):
        B, Lq = query.shape[0], query.shape[1]
        if not self._cross:
            if kv is not None and kv is not query:
                raise ValueError(
                    "this MultiHeadAttention was built for self-attention; "
                    "pass cross_attention=True to attend over a memory")
            q, k, v = self._heads(F, self.qkv(query), 3)
        else:
            if kv is None:
                kv = query
            q, = self._heads(F, self.q_proj(query), 1)
            k, v = self._heads(F, self.kv_proj(kv), 2)
        if mask is not None:
            out = F.dot_product_attention(q, k, v, mask, causal=self._causal)
        else:
            out = F.dot_product_attention(q, k, v, causal=self._causal)
        # (B, H, Lq, d) -> (B, Lq, C)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)), (B, Lq, self._units))
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class PositionwiseFFN(HybridBlock):
    """The transformer MLP: dense(hidden) -> act -> dense(units) -> dropout."""

    def __init__(self, units: int, hidden_size: int, dropout: float = 0.0,
                 activation: str = "gelu", dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                                 activation=activation, dtype=dtype,
                                 prefix="ffn1_",
                                 weight_initializer=weight_initializer)
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                                 dtype=dtype, prefix="ffn2_",
                                 weight_initializer=weight_initializer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn2(self.ffn1(x))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-LN transformer encoder layer (BERT layout):
    ``x = LN(x + MHA(x)); x = LN(x + FFN(x))``."""

    def __init__(self, units: int, hidden_size: int, num_heads: int,
                 dropout: float = 0.0, layer_norm_eps: float = 1e-12,
                 activation: str = "gelu", dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(
                units, num_heads, dropout=dropout, dtype=dtype,
                prefix="attn_", weight_initializer=weight_initializer)
            self.ln1 = nn.LayerNorm(epsilon=layer_norm_eps, prefix="ln1_")
            self.ffn = PositionwiseFFN(
                units, hidden_size, dropout=dropout, activation=activation,
                dtype=dtype, prefix="ffn_",
                weight_initializer=weight_initializer)
            self.ln2 = nn.LayerNorm(epsilon=layer_norm_eps, prefix="ln2_")

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.attention(x, None, mask))
        x = self.ln2(x + self.ffn(x))
        return x


class StackedTransformerEncoder(HybridBlock):
    """Scan-over-layers transformer encoder: every parameter carries a
    leading ``(num_layers,)`` axis, the forward is a ``lax.scan`` over that
    axis — the production-JAX formulation of a deep stack (one compiled
    layer body regardless of depth).

    This layout is what makes PIPELINE parallelism a pure sharding choice:
    with an active mesh whose ``pp`` axis divides ``num_layers``, the layer
    stack becomes ``pp`` stages of ``num_layers/pp`` layers and the forward
    runs the microbatched GPipe schedule (``parallel/pipeline.py``), the
    stage stacks sharded over ``pp``. Without pp it is an ordinary scan.
    Reference counterpart: none — SURVEY §2.5 parity-plus extension.
    """

    def __init__(self, num_layers: int, units: int, hidden_size: int,
                 num_heads: int, layer_norm_eps: float = 1e-12,
                 n_micro: int = 4, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._N = num_layers
        self._units = units
        self._hidden = hidden_size
        self._heads = num_heads
        self._eps = layer_norm_eps
        self._n_micro = n_micro
        N, C, H = num_layers, units, hidden_size
        with self.name_scope():
            get = self.params.get
            self.qkv_w = get("qkv_weight", shape=(N, 3 * C, C), init="xavier",
                             dtype=dtype)
            self.qkv_b = get("qkv_bias", shape=(N, 3 * C), init="zeros",
                             dtype=dtype)
            self.proj_w = get("proj_weight", shape=(N, C, C), init="xavier",
                              dtype=dtype)
            self.proj_b = get("proj_bias", shape=(N, C), init="zeros",
                              dtype=dtype)
            self.ffn1_w = get("ffn1_weight", shape=(N, H, C), init="xavier",
                              dtype=dtype)
            self.ffn1_b = get("ffn1_bias", shape=(N, H), init="zeros",
                              dtype=dtype)
            self.ffn2_w = get("ffn2_weight", shape=(N, C, H), init="xavier",
                              dtype=dtype)
            self.ffn2_b = get("ffn2_bias", shape=(N, C), init="zeros",
                              dtype=dtype)
            self.ln1_g = get("ln1_gamma", shape=(N, C), init="ones",
                             dtype=dtype)
            self.ln1_b = get("ln1_beta", shape=(N, C), init="zeros",
                             dtype=dtype)
            self.ln2_g = get("ln2_gamma", shape=(N, C), init="ones",
                             dtype=dtype)
            self.ln2_b = get("ln2_beta", shape=(N, C), init="zeros",
                             dtype=dtype)

    # -- one layer on one (mb, L, C) block ---------------------------------
    def _layer(self, p, x):
        C, Hd = self._units, self._heads
        D = C // Hd
        B, L, _ = x.shape

        def ln(v, g, b):
            mu = v.mean(-1, keepdims=True)
            var = ((v - mu) ** 2).mean(-1, keepdims=True)
            return (v - mu) * jax.lax.rsqrt(var + self._eps) * g + b

        qkv = jnp.einsum("blc,oc->blo", x, p["qkv_w"]) + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, L, Hd, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, L, Hd, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, L, Hd, D).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * (D ** -0.5)
        a = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, L, C)
        o = jnp.einsum("blc,oc->blo", o, p["proj_w"]) + p["proj_b"]
        x = ln(x + o, p["ln1_g"], p["ln1_b"])
        h = jax.nn.gelu(jnp.einsum("blc,hc->blh", x, p["ffn1_w"])
                        + p["ffn1_b"], approximate=False)
        f = jnp.einsum("blh,ch->blc", h, p["ffn2_w"]) + p["ffn2_b"]
        return ln(x + f, p["ln2_g"], p["ln2_b"])

    def _params_tree(self, kw):
        names = ["qkv_w", "qkv_b", "proj_w", "proj_b", "ffn1_w", "ffn1_b",
                 "ffn2_w", "ffn2_b", "ln1_g", "ln1_b", "ln2_g", "ln2_b"]
        from ..ndarray import NDArray
        return {n: (kw[n]._data if isinstance(kw[n], NDArray) else kw[n])
                for n in names}

    def hybrid_forward(self, F, x, **kw):
        from ..ndarray import NDArray
        from ..parallel.mesh import current_active_mesh
        xv = x._data if isinstance(x, NDArray) else x
        tree = self._params_tree(kw)
        mesh = current_active_mesh()
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        use_pp = (pp > 1 and self._N % pp == 0
                  and isinstance(xv, jax.core.Tracer)
                  and xv.shape[0] % self._n_micro == 0)
        if use_pp:
            from functools import partial
            from jax.sharding import PartitionSpec as P
            from ..parallel.collectives import shard_map
            from ..parallel.pipeline import pipeline_apply
            per_stage = self._N // pp
            M = self._n_micro
            B = xv.shape[0]
            stage = {n: v.reshape((pp, per_stage) + v.shape[1:])
                     for n, v in tree.items()}

            def stage_fn(p, mb):
                def body(h, i):
                    pl = jax.tree.map(lambda v: v[i], p)
                    return self._layer(pl, h), None
                out, _ = jax.lax.scan(body, mb, jnp.arange(per_stage))
                return out

            xm = xv.reshape((M, B // M) + xv.shape[1:])
            dp = mesh.shape.get("dp", 1)
            use_dp = dp > 1 and (B // M) % dp == 0
            xspec = P(None, "dp" if use_dp else None)
            pspec = {n: P("pp") for n in stage}
            fn = shard_map(partial(pipeline_apply, stage_fn=stage_fn,
                                   axis="pp"),
                           mesh=mesh, in_specs=(pspec, xspec),
                           out_specs=xspec)
            out = fn(stage, xm)
            out = out.reshape(xv.shape)
        else:
            def body(h, i):
                pl = jax.tree.map(lambda v: v[i], tree)
                return self._layer(pl, h), None
            out, _ = jax.lax.scan(body, xv, jnp.arange(self._N))
        return NDArray(out, ctx=x.context) if isinstance(x, NDArray) else out

"""Transformer building blocks shared by BERT and the NMT Transformer.

Reference parity: GluonNLP's ``BERTEncoder``/``TransformerEncoderCell``
(gluonnlp/model/bert.py, transformer.py), whose hot path is the contrib
interleaved-MHA ops (``src/operator/contrib/transformer.cc`` — SURVEY §2.4).

TPU-native design: one fused QKV projection (a single MXU matmul over the
batch·seq rows) followed by :func:`~incubator_mxnet_tpu.ops.attention.
dot_product_attention` — which lowers to the Pallas flash kernel on TPU. The
reference's (B·H, L, L) score tensor never exists in HBM.
"""
from __future__ import annotations

from typing import Optional

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell"]


class MultiHeadAttention(HybridBlock):
    """Multi-head attention with fused QKV projection.

    ``__call__(query, kv, mask)`` — pass ``kv=None`` (or ``query``) for
    self-attention (one fused qkv matmul); a different ``kv`` gives
    cross-attention (q proj + fused kv proj, the encdec layout of the
    reference's ``interleaved_matmul_encdec_*`` ops).

    ``mask`` is broadcastable to (B, H, Lq, Lk), 1 = attend; ``None`` = full.
    """

    def __init__(self, units: int, num_heads: int, dropout: float = 0.0,
                 causal: bool = False, use_bias: bool = True, dtype="float32",
                 cross_attention: bool = False, weight_initializer=None,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._cross = cross_attention
        with self.name_scope():
            # Only the projections this cell actually uses exist — dead
            # parameters would get optimizer state and distort MFU accounting.
            if cross_attention:
                self.q_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                       in_units=units, dtype=dtype,
                                       prefix="query_",
                                       weight_initializer=weight_initializer)
                self.kv_proj = nn.Dense(2 * units, flatten=False,
                                        use_bias=use_bias, in_units=units,
                                        dtype=dtype, prefix="kv_",
                                        weight_initializer=weight_initializer)
            else:
                self.qkv = nn.Dense(3 * units, flatten=False, use_bias=use_bias,
                                    in_units=units, dtype=dtype, prefix="qkv_",
                                    weight_initializer=weight_initializer)
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 in_units=units, dtype=dtype, prefix="proj_",
                                 weight_initializer=weight_initializer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def _heads(self, F, x, n):
        # (B, L, n*C) -> n tensors of (B, H, L, d)
        B, L = x.shape[0], x.shape[1]
        H, d = self._num_heads, self._units // self._num_heads
        parts = F.split(x, num_outputs=n, axis=2) if n > 1 else [x]
        outs = []
        for p in parts:
            outs.append(F.transpose(F.reshape(p, (B, L, H, d)), axes=(0, 2, 1, 3)))
        return outs

    def hybrid_forward(self, F, query, kv=None, mask=None):
        B, Lq = query.shape[0], query.shape[1]
        if not self._cross:
            if kv is not None and kv is not query:
                raise ValueError(
                    "this MultiHeadAttention was built for self-attention; "
                    "pass cross_attention=True to attend over a memory")
            q, k, v = self._heads(F, self.qkv(query), 3)
        else:
            if kv is None:
                kv = query
            q, = self._heads(F, self.q_proj(query), 1)
            k, v = self._heads(F, self.kv_proj(kv), 2)
        if mask is not None:
            out = F.dot_product_attention(q, k, v, mask, causal=self._causal)
        else:
            out = F.dot_product_attention(q, k, v, causal=self._causal)
        # (B, H, Lq, d) -> (B, Lq, C)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)), (B, Lq, self._units))
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class PositionwiseFFN(HybridBlock):
    """The transformer MLP: dense(hidden) -> act -> dense(units) -> dropout."""

    def __init__(self, units: int, hidden_size: int, dropout: float = 0.0,
                 activation: str = "gelu", dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                                 activation=activation, dtype=dtype,
                                 prefix="ffn1_",
                                 weight_initializer=weight_initializer)
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                                 dtype=dtype, prefix="ffn2_",
                                 weight_initializer=weight_initializer)
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn2(self.ffn1(x))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-LN transformer encoder layer (BERT layout):
    ``x = LN(x + MHA(x)); x = LN(x + FFN(x))``."""

    def __init__(self, units: int, hidden_size: int, num_heads: int,
                 dropout: float = 0.0, layer_norm_eps: float = 1e-12,
                 activation: str = "gelu", dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(
                units, num_heads, dropout=dropout, dtype=dtype,
                prefix="attn_", weight_initializer=weight_initializer)
            self.ln1 = nn.LayerNorm(epsilon=layer_norm_eps, prefix="ln1_")
            self.ffn = PositionwiseFFN(
                units, hidden_size, dropout=dropout, activation=activation,
                dtype=dtype, prefix="ffn_",
                weight_initializer=weight_initializer)
            self.ln2 = nn.LayerNorm(epsilon=layer_norm_eps, prefix="ln2_")

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.attention(x, None, mask))
        x = self.ln2(x + self.ffn(x))
        return x

"""RecordIO — packed binary record format.

Reference parity: dmlc-core recordio (``dmlc::RecordIOWriter/Reader``) and
``python/mxnet/recordio.py`` (``MXRecordIO``, ``MXIndexedRecordIO``,
``IRHeader``/``pack``/``unpack``/``pack_img``/``unpack_img``) — SURVEY §2.6.

Wire format (same as dmlc recordio, so `.rec` files interoperate):
each record is ``uint32 magic (0xced7230a)``, ``uint32 lrecord`` where the
upper 3 bits are a continuation flag and the lower 29 bits the payload
length, then the payload padded to a 4-byte boundary. Payloads here never
use continuation (cflag=0) — dmlc only needs it when the payload contains
the magic, which it escapes by splitting; readers of our files see single
complete records, and our reader handles dmlc-split records by
reassembling.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple
from typing import Dict, List, Optional

import numpy as onp

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_KMAGIC = struct.pack("<I", _MAGIC)


def _lrec(length: int, cflag: int) -> int:
    return (cflag << 29) | length


def _decode_lrec(lrec: int):
    return lrec & ((1 << 29) - 1), lrec >> 29


def _native():
    """The C++ recordio parser (native/mxtpu_native.cc) when buildable."""
    if os.environ.get("MXTPU_NO_NATIVE"):
        return None
    try:
        from . import native
        return native if native.available() else None
    except Exception:
        return None


class MXRecordIO:
    """Sequential .rec reader/writer (reference: dmlc::RecordIOWriter).

    Uses the C++ parser (native/mxtpu_native.cc — the src/io/ counterpart)
    when available; the pure-Python path below is the fallback and the
    format specification."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self._nat = None
        self.open()

    def open(self):
        nat = _native()
        if self.flag == "w":
            self.writable = True
            if nat is not None:
                self._nat = nat.NativeRecordWriter(self.uri)
                self.handle = None
            else:
                self.handle = open(self.uri, "wb")
        elif self.flag == "r":
            self.writable = False
            if nat is not None:
                self._nat = nat.NativeRecordReader(self.uri)
                self.handle = None
            else:
                self.handle = open(self.uri, "rb")
        else:
            raise MXNetError(f"Invalid flag {self.flag!r} (use 'r' or 'w')")
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._nat is not None:
                self._nat.close()
                self._nat = None
            if self.handle is not None:
                self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        if self._nat is not None:
            return self._nat.tell()
        return self.handle.tell()

    def seek(self, pos: int):
        if self._nat is not None:
            self._nat.seek(pos)
        else:
            self.handle.seek(pos)

    def write(self, buf: bytes) -> int:
        """Append one record; returns its byte offset."""
        if not self.writable:
            raise MXNetError("recordio not opened for writing")
        if self._nat is not None:
            return self._nat.write(buf)
        pos = self.handle.tell()
        # NB: unlike the native writer this simple path does not split
        # payloads containing the magic; the reader handles both layouts.
        self.handle.write(_KMAGIC)
        self.handle.write(struct.pack("<I", _lrec(len(buf), 0)))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)
        return pos

    def read(self) -> Optional[bytes]:
        if self.writable:
            raise MXNetError("recordio not opened for reading")
        if self._nat is not None:
            return self._nat.read()
        parts: List[bytes] = []
        while True:
            head = self.handle.read(8)
            if len(head) < 8:
                return b"".join(parts) if parts else None
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError("invalid record magic — corrupt .rec file")
            length, cflag = _decode_lrec(lrec)
            data = self.handle.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            parts.append(data)
            # cflag: 0 whole, 1 start, 2 middle, 3 end. dmlc's writer splits
            # a payload at embedded magic bytes (removing them); its reader
            # re-inserts the magic between parts — so must we.
            if cflag in (0, 3):
                return parts[0] if len(parts) == 1 else _KMAGIC.join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec via .idx sidecar (reference: MXIndexedRecordIO).
    The idx file is ``key\\tbyte_offset`` per line, tool-compatible with
    im2rec output."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx: Dict = {}
        self.keys: List = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    key, pos = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if getattr(self, "writable", False) and getattr(self, "is_open", False):
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        MXRecordIO.seek(self, self.idx[idx])

    def read_idx(self, idx) -> bytes:
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf: bytes):
        pos = self.write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a label header + payload (reference: recordio.pack). Multi-label
    goes in ``flag`` = label count with labels prepended as float32s."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (onp.ndarray, list, tuple)):
        label = onp.asarray(label, dtype=onp.float32)
        header = header._replace(flag=label.size, label=0.0)
        payload = label.tobytes() + s
    else:
        payload = s
    return struct.pack(_IR_FORMAT, int(header.flag), float(header.label),
                       int(header.id), int(header.id2)) + payload


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        n = header.flag
        label = onp.frombuffer(payload[:4 * n], dtype=onp.float32)
        header = header._replace(label=label)
        payload = payload[4 * n:]
    return header, payload


def encode_img(img, quality: int = 95, img_fmt: str = ".jpg") -> bytes:
    """Encode an image to jpg/png bytes (the cv2 half of pack_img; shared
    with the native im2rec packer so both paths stay byte-identical)."""
    import cv2
    params = [cv2.IMWRITE_JPEG_QUALITY, quality] if img_fmt in (".jpg", ".jpeg") \
        else [cv2.IMWRITE_PNG_COMPRESSION, quality // 10]
    ok, buf = cv2.imencode(img_fmt, img, params)
    if not ok:
        raise MXNetError(f"failed to encode image as {img_fmt}")
    return buf.tobytes()


def pack_img(header: IRHeader, img, quality: int = 95, img_fmt: str = ".jpg") -> bytes:
    return pack(header, encode_img(img, quality=quality, img_fmt=img_fmt))


def unpack_img(s: bytes, iscolor: int = -1):
    import cv2
    header, payload = unpack(s)
    img = cv2.imdecode(onp.frombuffer(payload, dtype=onp.uint8), iscolor)
    return header, img

"""Runtime feature discovery.

Reference parity: ``mx.runtime.Features()`` / ``MXLibInfoFeatures``
(``src/libinfo.cc`` — SURVEY §5.6): lets user/test code probe what this build
supports. The TPU build reports accelerator topology instead of CUDA/MKLDNN
compile flags.
"""
from __future__ import annotations

from typing import Dict, List

import jax

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect() -> Dict[str, bool]:
    devices = jax.devices()
    platforms = {d.platform for d in devices}
    has_tpu = "tpu" in platforms
    feats = {
        "TPU": has_tpu,
        "CUDA": False, "CUDNN": False, "NCCL": False, "TENSORRT": False,
        "MKLDNN": False, "CPU": True,
        "XLA": True,
        "PALLAS": has_tpu,
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        "DIST_KVSTORE": True,            # jax.distributed multi-controller
        "SIGNAL_HANDLER": True,
        "OPENCV": _has("cv2"),
        "F16C": False,
        "FLASH_ATTENTION": has_tpu,
        "MESH_SPMD": True,
        "PROFILER": True,
    }
    return feats


def _has(mod: str) -> bool:
    try:
        __import__(mod)
        return True
    except Exception:
        return False


class Features(dict):
    """dict-like: ``fts = mx.runtime.Features(); fts.is_enabled('TPU')``."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name: str) -> bool:
        f = self.get(name.upper())
        return bool(f and f.enabled)

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"


def feature_list() -> List[Feature]:
    return list(Features().values())

"""Device Context model.

TPU-native counterpart of ``include/mxnet/base.h (mxnet::Context)`` and
``python/mxnet/context.py``. The north star (BASELINE.json) asks for TPU as a
first-class Context: ``mx.tpu()``. Under JAX, a Context maps onto a concrete
``jax.Device``; NDArray storage lives in PjRt device buffers addressed by it.

Differences from the reference, by design:
- ``gpu`` is accepted as an alias of the accelerator context so that reference
  scripts run unchanged on TPU machines (``mx.gpu(0)`` → accelerator 0).
- ``cpu_pinned``/``cpu_shared`` map to plain host CPU; PjRt manages pinned
  staging internally and DataLoader sharing uses OS shm at the io layer.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "cpu_pinned",
    "cpu_shared",
    "current_context",
    "num_gpus",
    "num_tpus",
    "gpu_memory_info",
    "tpu_memory_info",
    "memory_stats",
]


def _accel_platforms() -> List[str]:
    return [p for p in ("tpu", "axon", "gpu", "cuda", "rocm")]


def _devices_for(dev_type: str) -> List[jax.Device]:
    """Concrete jax devices backing a context type.

    device_id is WORKER-LOCAL (reference: each dmlc worker numbers its own
    GPUs from 0) — in a multi-controller run only this process's devices are
    addressable, so contexts index ``jax.local_devices()``.
    """
    all_devices = jax.local_devices()
    if dev_type in ("cpu", "cpu_pinned", "cpu_shared"):
        local_cpu = [d for d in all_devices if d.platform == "cpu"]
        if local_cpu:
            return local_cpu
        try:
            # default backend is an accelerator: this process's CPU devices
            # live on the cpu backend (still worker-local).
            return jax.local_devices(backend="cpu")
        except RuntimeError:
            # CPU platform absent (rare) — fall back to default devices.
            return all_devices
    # accelerator types: tpu (and gpu as an alias)
    accel = [d for d in all_devices if d.platform not in ("cpu",)]
    if accel:
        return accel
    # No accelerator present: transparently fall back to CPU so that
    # device-parametrized test suites (SURVEY §4.1) run everywhere.
    return _devices_for("cpu") if _has_cpu() else all_devices


def _has_cpu() -> bool:
    try:
        jax.devices("cpu")
        return True
    except RuntimeError:
        return False


class Context:
    """A device context ``(device_type, device_id)``.

    Reference parity: ``mxnet::Context`` devtype ids (kCPU=1, kGPU=2,
    kCPUPinned=3, kCPUShared=5) plus the new first-class kTPU=6.
    """

    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}

    _default = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_id = device_type.device_id
            device_type = device_type.device_type
        if device_type not in self.devtype2id:
            raise MXNetError(f"Unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = device_id

    # -- identity ----------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devtype2id[self.device_type]

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()

    # -- jax mapping -------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        devs = _devices_for(self.device_type)
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self}: device_id out of range, only {len(devs)} "
                f"device(s) of this type are visible"
            )
        return devs[self.device_id]

    @property
    def is_accelerator(self) -> bool:
        return self.jax_device.platform != "cpu"

    # -- scoping -----------------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default, "stack"):
            Context._default.stack = []
        Context._default.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default.stack.pop()

    def empty_cache(self):
        """Reference parity: ``Context.empty_cache`` — PjRt manages pooling;
        trigger a GC of unreferenced buffers."""
        import gc

        gc.collect()

    def memory_stats(self) -> dict:
        """Memory stats for this context's device: PjRt
        ``device.memory_stats()`` where the backend exposes them
        (``source="pjrt"``), else the ``telemetry.memory`` ledger's view
        — live-array residency on the device, ``MXTPU_HBM_BUDGET`` as
        the limit (``source="ledger"``) — so reference scripts read
        real numbers on every backend instead of hitting the PjRt
        stub."""
        dev = self.jax_device
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            return dict(stats, source="pjrt")
        from .telemetry import memory as _memory
        used = _memory.device_bytes(dev)
        budget = _memory.hbm_budget()
        return {"bytes_in_use": used,
                "bytes_limit": budget if budget else used,
                "source": "ledger"}


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias of the accelerator context (reference scripts using ``mx.gpu``
    transparently target TPU here)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def current_context() -> Context:
    stack = getattr(Context._default, "stack", None)
    if stack:
        return stack[-1]
    return cpu(0)


def context_for_device(device) -> Context:
    """Context addressing a concrete jax.Device (e.g. a mesh's first device)."""
    dev_type = "cpu" if device.platform == "cpu" else "tpu"
    peers = _devices_for(dev_type)
    try:
        idx = peers.index(device)
    except ValueError:
        idx = 0
    return Context(dev_type, idx)


def num_gpus() -> int:
    """Number of accelerator devices visible (alias surface)."""
    return num_tpus()


def gpu_memory_info(device_id: int = 0):
    """``(free, total)`` bytes on the accelerator, reference
    ``python/mxnet/context.py (gpu_memory_info)`` / C API
    ``MXGetGPUMemoryInformation64``. On TPU the numbers come from PjRt's
    ``memory_stats`` (HBM); alias name kept so reference scripts run
    unchanged. Backends exposing no PjRt stats (pure-CPU test runs) fall
    back to the ``telemetry.memory`` ledger — live-array residency as
    "used", ``MXTPU_HBM_BUDGET`` as "total" — so the call reports real
    numbers everywhere instead of raising on the PjRt stub."""
    return tpu_memory_info(device_id)


def memory_stats(device_id: int = 0) -> dict:
    """Module-level alias of :meth:`Context.memory_stats` for the
    accelerator context (reference scripts call it off ``mx.context``)."""
    return tpu(device_id).memory_stats()


def tpu_memory_info(device_id: int = 0):
    devs = _devices_for("tpu")
    if not 0 <= device_id < len(devs):
        raise MXNetError(
            f"device_id {device_id} out of range ({len(devs)} devices)")
    stats = None
    try:
        stats = devs[device_id].memory_stats()
    except Exception:
        stats = None
    if stats:
        total = stats.get("bytes_limit", 0)
        used = stats.get("bytes_in_use", 0)
        return (total - used, total)
    # no PjRt stats on this backend: the telemetry.memory ledger is the
    # source of truth — residency measured off jax.live_arrays(), the
    # configured HBM budget as capacity (used = total when unbudgeted,
    # i.e. free reads 0 rather than a made-up number)
    from .telemetry import memory as _memory
    used = _memory.device_bytes(devs[device_id])
    budget = _memory.hbm_budget()
    total = budget if budget else used
    return (max(total - used, 0), total)


def num_tpus() -> int:
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return len(devs)

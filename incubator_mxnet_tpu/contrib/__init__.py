"""``mx.contrib`` — contrib op namespaces.

Reference parity: ``python/mxnet/contrib/`` — ``mx.contrib.nd.<op>`` and
``mx.contrib.sym.<op>`` views over the ``_contrib_*`` registered ops
(SURVEY §2.4 contrib subtree: transformer fused attention, bounding-box/
MultiBox detection ops, ROIAlign).
"""
from __future__ import annotations

import sys
import types

from ..ops.registry import OPS
from ..ndarray.op import make_nd_op

__all__ = ["nd", "sym", "summary"]


def _contrib_names():
    out = {}
    for name, opdef in OPS.items():
        if name.startswith("_contrib_"):
            out[name[len("_contrib_"):]] = opdef
    return out


nd = types.ModuleType("incubator_mxnet_tpu.contrib.nd")
for _short, _opdef in _contrib_names().items():
    setattr(nd, _short, make_nd_op(_opdef))
# control flow (reference: python/mxnet/ndarray/contrib.py)
from ..ops import control_flow as _cf  # noqa: E402
nd.foreach = _cf.foreach
nd.while_loop = _cf.while_loop
nd.cond = _cf.cond
sys.modules[nd.__name__] = nd


def _make_sym(opname):
    def sym_op(*args, name=None, **kwargs):
        from .. import symbol as S
        ins = [a for a in args if isinstance(a, S.Symbol)]
        return S.Symbol(opname, ins, attrs=kwargs, name=name)
    sym_op.__name__ = opname
    return sym_op


sym = types.ModuleType("incubator_mxnet_tpu.contrib.sym")
for _short, _opdef in _contrib_names().items():
    setattr(sym, _short, _make_sym(_opdef.name))
# control flow (reference: python/mxnet/symbol/contrib.py)
sym.foreach = _cf.sym_foreach
sym.while_loop = _cf.sym_while_loop
sym.cond = _cf.sym_cond
sys.modules[sym.__name__] = sym


def __getattr__(name):
    # mx.contrib.quantization — lazy (reference: contrib/quantization.py)
    if name == "quantization":
        import importlib
        mod = importlib.import_module("..quantization", __name__)
        globals()[name] = mod
        return mod
    if name == "summary":
        # mxboard-parity SummaryWriter — lazy so mx.contrib.nd users never
        # pay the onnx codec import
        import importlib
        mod = importlib.import_module(".summary", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

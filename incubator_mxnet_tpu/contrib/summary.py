"""TensorBoard SummaryWriter (mxboard parity).

Reference ecosystem counterpart: the external ``mxboard`` package
(``SummaryWriter.add_scalar/add_histogram``) the reference's training
scripts log with (SURVEY §5.5 names it as the observability gap next to
Speedometer). Self-contained: TensorBoard's event-file format is
length-framed records with masked CRC-32C checksums wrapping ``Event``
protobufs — both the protobuf encoding (reusing the in-tree codec helpers,
``onnx/_proto.py``) and CRC-32C are implemented here, so files open in
stock TensorBoard without any external dependency.

Usage::

    from incubator_mxnet_tpu.contrib.summary import SummaryWriter
    with SummaryWriter(logdir="./logs") as sw:
        sw.add_scalar("loss", float(loss.asnumpy()), global_step=step)
        sw.add_histogram("fc1_weight", net.fc1.weight.data(), step)
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

import numpy as onp

from ..onnx._proto import _f32_field, _len_delim, _tag, _vint_field

__all__ = ["SummaryWriter"]


# ---------------------------------------------------------------------------
# CRC-32C (Castagnoli), TFRecord masking — TensorBoard validates these
# ---------------------------------------------------------------------------

def _build_crc_table():
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


# built at import: a lazily-built list is racy under concurrent first use
_CRC_TABLE = _build_crc_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Event / Summary / HistogramProto encoding (tensorboard.proto field numbers)
# ---------------------------------------------------------------------------

def _f64_field(fieldno: int, value: float) -> bytes:
    return _tag(fieldno, 1) + struct.pack("<d", float(value))


def _packed_f64(fieldno: int, values) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in values)
    return _len_delim(fieldno, payload)


def _summary_value_scalar(tag: str, value: float) -> bytes:
    v = _len_delim(1, tag.encode()) + _f32_field(2, value)
    return _len_delim(1, v)          # Summary.value


def _histogram(values: onp.ndarray, bins: int = 30) -> bytes:
    v = onp.asarray(values, dtype=onp.float64).ravel()
    # a diverged run must not crash its own logging: drop non-finite
    # entries; an empty result records an empty histogram
    v = v[onp.isfinite(v)]
    if v.size == 0:
        return (_f64_field(1, 0.0) + _f64_field(2, 0.0) + _f64_field(3, 0.0)
                + _f64_field(4, 0.0) + _f64_field(5, 0.0))
    counts, edges = onp.histogram(v, bins=bins)
    body = (_f64_field(1, float(v.min())) + _f64_field(2, float(v.max())) +
            _f64_field(3, float(v.size)) + _f64_field(4, float(v.sum())) +
            _f64_field(5, float((v * v).sum())) +
            _packed_f64(6, edges[1:]) + _packed_f64(7, counts))
    return body


def _summary_value_histo(tag: str, values, bins: int) -> bytes:
    v = _len_delim(1, tag.encode()) + _len_delim(5, _histogram(values, bins))
    return _len_delim(1, v)


def _event(wall_time: float, step: int, payload: bytes = b"",
           file_version: Optional[str] = None) -> bytes:
    out = _f64_field(1, wall_time) + _vint_field(2, step)
    if file_version is not None:
        out += _len_delim(3, file_version.encode())
    if payload:
        out += _len_delim(5, payload)    # Event.summary
    return out


class SummaryWriter:
    """Append-only event-file writer; one file per writer instance."""

    _seq = 0

    def __init__(self, logdir: str = "./logs", flush_secs: int = 120,
                 filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        # pid + per-process counter uniquify the name: two writers created
        # in the same wall-clock second must not truncate each other
        SummaryWriter._seq += 1
        fname = "events.out.tfevents.%010d.%s.%d.%d%s" % (
            int(time.time()), socket.gethostname(), os.getpid(),
            SummaryWriter._seq, filename_suffix)
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "wb")
        self._flush_secs = flush_secs
        self._last_flush = time.time()
        # the mandatory version header record
        self._write_event(_event(time.time(), 0, file_version="brain.Event:2"))

    # -- record framing ----------------------------------------------------
    def _write_event(self, event: bytes) -> None:
        header = struct.pack("<Q", len(event))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(event)
        self._f.write(struct.pack("<I", _masked_crc(event)))
        if time.time() - self._last_flush > self._flush_secs:
            self.flush()

    # -- public API (mxboard names) ---------------------------------------
    def add_scalar(self, tag: str, value, global_step: int = 0) -> None:
        value = float(value.asnumpy()) if hasattr(value, "asnumpy") \
            else float(value)
        self._write_event(_event(time.time(), int(global_step),
                                 _summary_value_scalar(tag, value)))

    def add_histogram(self, tag: str, values, global_step: int = 0,
                      bins: int = 30) -> None:
        if hasattr(values, "asnumpy"):
            values = values.asnumpy()
        self._write_event(_event(time.time(), int(global_step),
                                 _summary_value_histo(tag, values, bins)))

    def flush(self) -> None:
        self._f.flush()
        self._last_flush = time.time()

    def close(self) -> None:
        if self._f:
            self.flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def logdir_file(self) -> str:
        return self._path

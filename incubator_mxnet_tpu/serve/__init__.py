"""``mx.serve`` — the compiled inference engine and serving runtime.

Reference counterpart: inference in MXNet 1.x was ``CachedOp`` replay
(``module.predict`` / exported symbol + MMS outside the framework). On a
jit-cache runtime the serving problem is different and sharper: **every
distinct request shape is an XLA compile**, so the subsystem's spine is
shape discipline (PyGraph's capture/replay argument, arXiv:2503.19779, and
TVM's ahead-of-time compiled deployment, arXiv:1802.04799, meet here):

========================  =============================================
:class:`BucketTable`      powers-of-two padded shape buckets per axis
:class:`CompiledModel`    frozen inference callable; ``warmup()`` AOT-
                          compiles every bucket; hit/miss/recompile
                          counters make "zero post-warmup recompiles"
                          an assertable contract
:class:`DynamicBatcher`   deadline-bounded coalescing of single requests
                          into bucket batches; bounded-queue backpressure
:class:`ModelRegistry`    versioned multi-model load/unload on
                          ``fault.checkpoint`` + ``fault.retry``; failed
                          loads never disturb the serving version
:class:`Server`           in-process + JSON-lines TCP front end
:class:`ServeMetrics`     p50/p95/p99 latency, queue depth, occupancy,
                          compile counters — JSON for the bench
:class:`Replica`          one independent worker (private registry +
                          batchers); crash/restart lifecycle
:class:`Router`           health-checked failover routing, retries +
                          hedging, admission control & load shedding,
                          training→serving weight pipe
:class:`ArtifactCache`    CRC-verified on-disk AOT artifacts so a
                          restarted replica prewarms with zero
                          post-restore compiles
:class:`DecodeEngine`     autoregressive generation: prefill/decode
                          split over a paged KV-cache whose capacity is
                          priced from ``MXTPU_HBM_BUDGET`` by the
                          liveness model (``serve.decode``)
:class:`DecodeBatcher`    continuous batching — requests join/leave the
                          running decode batch at token boundaries,
                          streaming tokens through :class:`TokenStream`
========================  =============================================

Minimal end-to-end::

    table = serve.BucketTable({"batch": (1, 8)})
    model = serve.CompiledModel(net, table, [{0: "batch"}],
                                example_args=(x,))
    model.warmup()                      # compiles every bucket
    out = model.predict(x)              # zero compiles from here on

    reg = serve.ModelRegistry()
    reg.load("mnist", table=table, input_axes=[{0: "batch"}],
             artifacts="deploy/lenet")  # cold start: StableHLO + params
    srv = serve.Server(reg).start()     # TCP on srv.port

Env knobs: ``MXTPU_SERVE_DEADLINE_MS``, ``MXTPU_SERVE_QUEUE_LIMIT``,
``MXTPU_SERVE_MAX_BATCH`` (see docs/env_vars.md).
"""
from __future__ import annotations

from .buckets import BucketOverflow, BucketTable, round_up_pow2  # noqa: F401
from .compiled import CompiledModel, export_for_serving  # noqa: F401
from .batcher import DynamicBatcher, QueueFullError, ServeFuture  # noqa: F401
from .metrics import ServeMetrics  # noqa: F401
from .registry import (ModelRegistry, ModelVersion,  # noqa: F401
                       apply_weights, map_checkpoint_arrays)
from .server import Server, client_call, client_generate  # noqa: F401
from .artifact_cache import (ArtifactCache,  # noqa: F401
                             ArtifactCorruptError, signature_key)
from .replica import Replica, ReplicaCrashed, ReplicaUnavailable  # noqa: F401
from .router import (DeadlineExceeded, ReplicaSet,  # noqa: F401
                     Router, ShedError, TokenRateBudget)
from . import decode  # noqa: F401
from .decode import (BlockPool, CacheExhausted, DecodeBatcher,  # noqa: F401
                     DecodeEngine, DecodeMetrics, TokenStream)

__all__ = ["BucketTable", "BucketOverflow", "round_up_pow2",
           "CompiledModel", "export_for_serving",
           "DynamicBatcher", "QueueFullError", "ServeFuture",
           "ServeMetrics", "ModelRegistry", "ModelVersion",
           "apply_weights", "map_checkpoint_arrays",
           "Server", "client_call", "client_generate",
           "ArtifactCache", "ArtifactCorruptError", "signature_key",
           "Replica", "ReplicaUnavailable", "ReplicaCrashed",
           "Router", "ReplicaSet", "ShedError", "DeadlineExceeded",
           "TokenRateBudget",
           "DecodeEngine", "DecodeBatcher", "TokenStream", "BlockPool",
           "CacheExhausted", "DecodeMetrics"]

"""Shape-bucket table — the serving answer to jit recompilation.

Reference counterpart: MXNet's bucketing Module (``BucketingModule``) kept
one executor per sequence-length bucket for variable-length RNN workloads;
on a jit-cache runtime the same idea is what makes serving viable at all:
every distinct input shape is a fresh XLA compile (seconds of latency — the
MX201 hazard ``analysis/recompile.py`` warns about), so raw request shapes
must be quantized onto a small closed set of padded shapes that
``CompiledModel.warmup()`` pre-compiles.

A :class:`BucketTable` declares *named* axes (``"batch"``, ``"seq"`` …)
with an inclusive ``(min, max)`` range each; bucket values are the
powers-of-two ladder clipped to that range, so the table for
``{"batch": (1, 8), "seq": (16, 64)}`` compiles exactly
``{1,2,4,8} x {16,32,64}`` graphs. Requests round *up* to the nearest
bucket and the pad rows/positions are sliced back off the outputs
(:meth:`CompiledModel.predict`), so padding is never visible to callers.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from ..base import MXNetError

__all__ = ["BucketTable", "BucketOverflow", "round_up_pow2"]


class BucketOverflow(MXNetError):
    """A request dimension exceeds the largest declared bucket — the
    caller must split the request (or the table must be widened and
    re-warmed)."""


def round_up_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    if n < 1:
        raise MXNetError(f"bucketed dimensions must be >= 1, got {n}")
    return 1 << (n - 1).bit_length()


class BucketTable:
    """Named bucketed axes with powers-of-two ladders.

    ``axes`` maps an axis name to its inclusive ``(min, max)`` size range;
    ``max`` is always a bucket even when it is not a power of two (so a
    model served at ``seq<=384`` does not silently pad to 512).
    """

    def __init__(self, axes: Dict[str, Tuple[int, int]]):
        if not axes:
            raise MXNetError("BucketTable needs at least one named axis")
        self.axes: Dict[str, Tuple[int, int]] = {}
        self._ladders: Dict[str, List[int]] = {}
        for name, (lo, hi) in axes.items():
            lo, hi = int(lo), int(hi)
            if lo < 1 or hi < lo:
                raise MXNetError(
                    f"axis {name!r}: need 1 <= min <= max, got ({lo}, {hi})")
            ladder = []
            v = round_up_pow2(lo)
            while v < hi:
                ladder.append(v)
                v *= 2
            ladder.append(hi)  # the declared max always closes the ladder
            self.axes[name] = (lo, hi)
            self._ladders[name] = ladder

    def sizes(self, name: str) -> List[int]:
        """The bucket ladder for one axis, ascending."""
        return list(self._ladders[name])

    def bucket(self, name: str, n: int) -> int:
        """Smallest bucket >= ``n`` for axis ``name``."""
        if name not in self._ladders:
            raise MXNetError(f"unknown bucket axis {name!r}; declared: "
                             f"{sorted(self._ladders)}")
        for v in self._ladders[name]:
            if v >= n:
                return v
        raise BucketOverflow(
            f"axis {name!r}: size {n} exceeds the largest bucket "
            f"{self._ladders[name][-1]}; split the request or widen the "
            "table")

    def assignment(self, sizes: Dict[str, int]) -> Dict[str, int]:
        """Bucket every named size at once: ``{"batch": 3, "seq": 20}`` →
        ``{"batch": 4, "seq": 32}``."""
        return {name: self.bucket(name, n) for name, n in sizes.items()}

    def assignments(self) -> Iterator[Dict[str, int]]:
        """Every bucket combination (cross product of the ladders) — the
        set :meth:`CompiledModel.warmup` pre-compiles, in deterministic
        (sorted-axis, ascending-size) order."""
        names = sorted(self._ladders)

        def rec(i: int, acc: Dict[str, int]):
            if i == len(names):
                yield dict(acc)
                return
            for v in self._ladders[names[i]]:
                acc[names[i]] = v
                yield from rec(i + 1, acc)

        yield from rec(0, {})

    def num_buckets(self) -> int:
        n = 1
        for ladder in self._ladders.values():
            n *= len(ladder)
        return n

    def __repr__(self):
        parts = ", ".join(f"{k}={self._ladders[k]}"
                          for k in sorted(self._ladders))
        return f"BucketTable({parts})"

"""ModelRegistry — versioned multi-model load/unload for the server.

Reference counterpart: MXNet Model Server (MMS) kept a model store beside
the framework; here the registry is framework-native so it can reuse the
fault runtime directly: weight loads go through
:func:`fault.checkpoint.load_latest` (newest *verified* checkpoint, walking
past corrupt steps) wrapped in :func:`fault.retry.call_with_retry`
(env-tunable backoff), and a failed load — including a chaos-injected one
(site ``"serve.registry.load"``) — NEVER disturbs the currently-serving
version: the new :class:`CompiledModel` is built and warmed completely
before the version table is touched.

Model sources per version:

- ``artifacts=`` path prefix of an ``export_for_serving`` artifact —
  the cold-start path: StableHLO + ``.params``, no Python model code;
- ``factory=`` zero-arg callable returning a (hybridizable) Block —
  the co-located path, traced through the same inference pure function;
- ``ckpt_root=`` optionally overrides either source's weights from the
  newest verified ``fault.checkpoint`` directory (training-time prefix
  names are mapped via the artifact manifest).

Version swap contract: same architecture + same bucket table ⇒ the swap
is :meth:`CompiledModel.refresh_params` — zero recompiles, asserted by the
serving tests via the compile-cache counters.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as onp

from ..base import MXNetError
from ..fault import checkpoint as fault_checkpoint
from ..lockcheck import make_rlock
from ..fault import inject
from ..fault.retry import RetryPolicy, call_with_retry
from .buckets import BucketTable
from .compiled import CompiledModel

__all__ = ["ModelRegistry", "ModelVersion", "map_checkpoint_arrays",
           "apply_weights"]


class ModelVersion:
    """One immutable (name, version) entry: the compiled model + source."""

    def __init__(self, name: str, version: int, compiled: CompiledModel,
                 source: Dict[str, Any]):
        self.name = name
        self.version = version
        self.compiled = compiled
        self.source = source

    def __repr__(self):
        return f"ModelVersion({self.name!r}, v{self.version})"


def map_checkpoint_arrays(arrays: Dict[str, onp.ndarray],
                          meta: dict) -> Dict[str, onp.ndarray]:
    """Checkpoint arrays → ``{param_name: array}``. Understands the
    ``gluon.Trainer``/``ShardedTrainer`` layout (``param:<i>`` arrays +
    ``meta["param_names"]``) as well as plain name-keyed array dicts
    (optimizer state is dropped either way). Shared by the registry's
    ``ckpt_root=`` loads and the router's live weight pipe."""
    names = meta.get("param_names")
    if names:  # trainer layout: positional params + recorded names
        out = {}
        for i, name in enumerate(names):
            key = f"param:{i:04d}"
            if key in arrays:
                out[name] = arrays[key]
        if out:
            return out
    return {k: v for k, v in arrays.items() if not k.startswith("opt:")}


def apply_weights(block, weights: Dict[str, onp.ndarray]) -> int:
    """Apply ``{param_name: array}`` onto a block — ``SymbolBlock``
    artifacts via ``set_weights`` (training-prefix name mapping included),
    live blocks via their collected parameters. Returns how many
    parameters were updated; the CALLER decides whether 0 is an error and
    must ``refresh_params()`` the wrapping :class:`CompiledModel`."""
    if hasattr(block, "set_weights"):
        return block.set_weights(weights, allow_missing=True,
                                 ignore_extra=True)
    params = block._collect_params_with_prefix()
    by_prefix = {p.name: p for p in params.values()}
    from ..ndarray import array as nd_array
    applied = 0
    for wname, val in weights.items():
        p = params.get(wname) or by_prefix.get(wname)
        if p is not None:
            p._load_init(nd_array(onp.asarray(val)), None)
            applied += 1
    return applied


def _weights_from_checkpoint(root: str, policy: Optional[RetryPolicy]
                             ) -> Dict[str, onp.ndarray]:
    """Newest verified checkpoint under ``root`` → ``{param_name: array}``
    via :func:`map_checkpoint_arrays`, retried under ``policy``."""
    def load():
        inject.crash("serve.registry.load")
        return fault_checkpoint.load_latest(root)

    arrays, meta, _step = call_with_retry(
        load, policy=policy, describe=f"checkpoint load from {root!r}")
    return map_checkpoint_arrays(arrays, meta)


class ModelRegistry:
    """Thread-safe, versioned model table. ``get(name)`` returns the
    active (newest unless pinned) version's :class:`CompiledModel`."""

    def __init__(self, retry_policy: Optional[RetryPolicy] = None):
        self._lock = make_rlock("ModelRegistry._lock")
        self._models: Dict[str, Dict[int, ModelVersion]] = {}
        self._active: Dict[str, int] = {}
        self._policy = retry_policy

    # -- loading --------------------------------------------------------
    def load(self, name: str, *, table: BucketTable,
             input_axes: Sequence[Dict[int, str]],
             artifacts: Optional[str] = None,
             factory: Optional[Callable[[], Any]] = None,
             example_args: Optional[Sequence] = None,
             ckpt_root: Optional[str] = None,
             version: Optional[int] = None,
             input_names: Optional[Sequence[str]] = None,
             epoch: int = 0, warmup: bool = True,
             output_axes: Optional[Sequence[Dict[int, str]]] = None,
             pad_values: Any = 0, analyze: bool = True,
             deadline_s: Optional[float] = None) -> ModelVersion:
        """Build, analyze, (optionally) warm and install one model version.

        Everything that can fail — artifact deserialization, checkpoint
        load (retried under the registry's policy), compiled-graph
        analysis, compilation, warmup — happens on a staging copy; the
        registry table is only touched on success, so the previously
        active version keeps serving through a failed load.

        ``analyze=True`` (default) runs the ``mx.analysis.hlo`` MX7xx
        passes over the staged model's bucket graphs BEFORE any warmup
        compile: error-severity findings (host callbacks in the graph,
        baked >1 MiB constants, unbucketed signatures) abort the load;
        warnings are published as a ``serve.analysis`` telemetry event.
        The same traced graphs feed the memory preflight: the summed
        bucket-ladder residency (``analysis.hlo.ladder_peak_bytes`` —
        weights once, per-bucket buffers summed) is emitted as a
        ``serve.memory`` event and, when ``MXTPU_HBM_BUDGET`` is set, an
        over-budget ladder is rejected at staging while the active
        version keeps serving.

        ``deadline_s`` bounds the whole staging build under a
        ``fault.watchdog`` deadline: a *hung* loader (not just a raising
        one — a wedged artifact read, a stuck factory) aborts with the
        active version still serving and a ``serve.load`` event with
        ``outcome="timeout"``. The stuck staging thread is left detached
        (daemon) — like an XLA dispatch, it cannot be safely interrupted.
        """
        if (artifacts is None) == (factory is None):
            raise MXNetError("pass exactly one of artifacts= (cold start "
                             "from an exported prefix) or factory= (live "
                             "Block constructor)")
        from ..telemetry import events as _tele
        auto_version = version is None
        with self._lock:
            if auto_version:
                have = self._models.get(name, {})
                version = max(have) + 1 if have else 1
            elif version in self._models.get(name, {}):
                raise MXNetError(f"{name!r} v{version} is already loaded; "
                                 "unload it first or omit version=")

        def stage():
            return self._stage(
                name, version, table=table, input_axes=input_axes,
                artifacts=artifacts, factory=factory,
                example_args=example_args, ckpt_root=ckpt_root,
                input_names=input_names, epoch=epoch, warmup=warmup,
                output_axes=output_axes, pad_values=pad_values,
                analyze=analyze)

        if deadline_s is None:
            compiled, source = stage()
        else:
            compiled, source = self._stage_with_deadline(
                stage, name, version, deadline_s)

        entry = ModelVersion(name, version, compiled, source)
        with self._lock:
            versions = self._models.setdefault(name, {})
            if version in versions:
                if not auto_version:
                    raise MXNetError(
                        f"{name!r} v{version} was loaded concurrently; "
                        "unload it first or omit version=")
                # a concurrent auto-versioned load took this slot during
                # staging — bump past it instead of overwriting
                version = max(versions) + 1
                entry.version = version
            versions[version] = entry
            pinned = self._active.get(name)
            if pinned is None or version > pinned:
                self._active[name] = version
        # the ACCEPTED version's predicted residency rides into OOM
        # bundles — noted only now, past every staging rejection path
        if source.get("ladder_peak_bytes") is not None:
            from ..telemetry import memory as _memory
            _memory.note_static_peak(f"serve:{name}",
                                     source["ladder_peak_bytes"])
        # emitted AFTER install so a concurrent auto-version bump cannot
        # put a version number on the stream the registry never held
        _tele.emit("serve.load", model=name, version=entry.version,
                   source=("artifacts" if artifacts is not None
                           else "factory"),
                   ckpt_root=ckpt_root, warmed=bool(warmup), outcome="ok")
        return entry

    def _stage(self, name: str, version: int, *, table, input_axes,
               artifacts, factory, example_args, ckpt_root, input_names,
               epoch, warmup, output_axes, pad_values, analyze):
        """The failable half of :meth:`load` — builds, analyzes and warms
        one :class:`CompiledModel` without touching the registry."""
        from ..telemetry import events as _tele
        if artifacts is not None:
            from ..gluon.block import SymbolBlock
            sym_file = f"{artifacts}-symbol.json"
            params_file = f"{artifacts}-{epoch:04d}.params"
            block = call_with_retry(
                lambda: SymbolBlock.imports(
                    sym_file, list(input_names or ["data"]), params_file),
                policy=self._policy,
                describe=f"artifact import from {artifacts!r}")
            if ckpt_root is not None:
                weights = _weights_from_checkpoint(ckpt_root, self._policy)
                applied = block.set_weights(weights, allow_missing=True,
                                            ignore_extra=True)
                if not applied:
                    # all names fell through the name mapping: the version
                    # would silently serve stale artifact weights while
                    # claiming checkpoint provenance
                    raise MXNetError(
                        f"checkpoint under {ckpt_root!r} matched 0 of the "
                        f"artifact's parameters (checkpoint names: "
                        f"{sorted(weights)[:4]}...; artifact names: "
                        f"{sorted(block._arch.get('param_order', []))[:4]}"
                        "...) — was it written by a trainer over a "
                        "different model or name scope?")
            source: Dict[str, Any] = {"artifacts": artifacts,
                                      "ckpt_root": ckpt_root}
        else:
            block = factory()
            if ckpt_root is not None:
                weights = _weights_from_checkpoint(ckpt_root, self._policy)
                if not apply_weights(block, weights):
                    raise MXNetError(
                        f"checkpoint under {ckpt_root!r} matched 0 of the "
                        f"factory model's parameters (checkpoint names: "
                        f"{sorted(weights)[:4]}...) — name-scope "
                        "mismatch?")
            source = {"factory": getattr(factory, "__name__", "factory"),
                      "ckpt_root": ckpt_root}

        compiled = CompiledModel(block, table, input_axes,
                                 example_args=example_args,
                                 output_axes=output_axes,
                                 pad_values=pad_values)
        if analyze:
            # pre-run lint of the artifact the device will execute: cheap
            # (tracing only, no XLA compile) and still on the staging
            # copy; max_graphs covers the FULL bucket table so the gate
            # never silently under-analyzes large tables
            from ..analysis import hlo as _hlo
            traced = _hlo.trace_entry(compiled,
                                      max_graphs=max(8,
                                                     table.num_buckets()))
            # memory preflight over the SAME traced graphs: the summed
            # bucket-ladder residency (weights once + every bucket's
            # buffers) vs MXTPU_HBM_BUDGET — the event is emitted before
            # any rejection so an over-budget ladder is visible on the
            # stream. The static peak is stashed on the source record
            # and noted for OOM forensics only AFTER the version is
            # installed (load()), so a REJECTED candidate never
            # overwrites the serving version's prediction.
            from ..analysis.hlo.cost import (_graph_param_bytes,
                                             _ladder_from_pairs)
            from ..telemetry import memory as _memory
            budget = _memory.hbm_budget()
            peaks = {g.site: _hlo.peak_live_bytes(g) for g in traced.graphs}
            ladder = _ladder_from_pairs(          # one scan, shared
                (_graph_param_bytes(g), peaks[g.site])
                for g in traced.graphs)
            _tele.emit("serve.memory", model=name, version=version,
                       ladder_peak_bytes=ladder, hbm_budget=budget,
                       buckets=peaks)
            source["ladder_peak_bytes"] = ladder
            # quant=True: the MX71x dtype-flow family runs on every
            # staged version — an un-calibrated (MX712) or
            # silently-promoted (MX711) int8 build is rejected here,
            # before its first device step, while the active version
            # keeps serving; float builds have no quantize boundaries
            # and pass through untouched
            rep = _hlo.verify_trace(traced, quant=True)
            if rep.diagnostics or rep.skipped:
                _tele.emit("serve.analysis", model=name, version=version,
                           **rep.summary_dict())
            if rep.errors:
                raise MXNetError(
                    f"analysis.hlo rejected {name!r} v{version} at "
                    "staging (the active version keeps serving):\n" +
                    "\n".join(f"  {d}" for d in rep.errors))
            if budget and ladder > budget:
                # the MX709 ladder rule usually catches this above; the
                # explicit check keeps the preflight airtight even when
                # a caller restricts the pass list
                raise MXNetError(
                    f"bucket ladder of {name!r} v{version} needs "
                    f"{ladder / 2**20:.1f} MiB resident, over the "
                    f"{budget / 2**20:.1f} MiB MXTPU_HBM_BUDGET — load "
                    "rejected at staging (the active version keeps "
                    "serving); trim the bucket table or raise the budget")
        if warmup:
            compiled.warmup()
        return compiled, source

    @staticmethod
    def _stage_with_deadline(stage: Callable, name: str, version: int,
                             deadline_s: float):
        """Run ``stage`` on a named daemon thread under a
        ``fault.watchdog`` deadline. A stuck loader — not raising, just
        never returning — aborts the load (``serve.load`` event with
        ``outcome="timeout"``) while the registry, and therefore the
        active version, stays untouched."""
        from ..fault.watchdog import Watchdog
        from ..telemetry import events as _tele
        box: Dict[str, Any] = {}

        def run():
            try:
                box["result"] = stage()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["exc"] = e

        t = threading.Thread(target=run,
                             name=f"mx-serve-stage-{name}-v{version}",
                             daemon=True)
        wd = Watchdog(deadline=deadline_s)
        with wd.watch(step=version):
            t.start()
            t.join(deadline_s)
        if t.is_alive():
            _tele.emit("serve.load", severity="error", model=name,
                       version=version, outcome="timeout",
                       deadline_s=deadline_s)
            raise MXNetError(
                f"staged load of {name!r} v{version} exceeded its "
                f"{deadline_s:.1f}s deadline; the active version keeps "
                f"serving (stuck loader thread {t.name!r} left detached "
                "— like an XLA dispatch it cannot be safely interrupted)")
        if "exc" in box:
            raise box["exc"]
        return box["result"]

    # -- lookup ---------------------------------------------------------
    def get(self, name: str, version: Optional[int] = None) -> CompiledModel:
        return self.get_version(name, version).compiled

    def get_version(self, name: str,
                    version: Optional[int] = None) -> ModelVersion:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise MXNetError(f"no model {name!r} in the registry "
                                 f"(loaded: {sorted(self._models)})")
            v = self._active[name] if version is None else version
            if v not in versions:
                raise MXNetError(f"{name!r} has no version {v} "
                                 f"(loaded: {sorted(versions)})")
            return versions[v]

    def models(self) -> Dict[str, List[int]]:
        with self._lock:
            return {n: sorted(vs) for n, vs in self._models.items()}

    def active_version(self, name: str) -> int:
        with self._lock:
            if name not in self._active:
                raise MXNetError(f"no model {name!r} in the registry")
            return self._active[name]

    # -- unloading ------------------------------------------------------
    def unload(self, name: str, version: Optional[int] = None) -> None:
        """Drop one version (or the whole model). Unloading the active
        version re-activates the newest remaining one."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise MXNetError(f"no model {name!r} in the registry")
            if version is None:
                del self._models[name]
                self._active.pop(name, None)
                return
            if version not in versions:
                raise MXNetError(f"{name!r} has no version {version}")
            del versions[version]
            if not versions:
                del self._models[name]
                self._active.pop(name, None)
            elif self._active.get(name) == version:
                self._active[name] = max(versions)

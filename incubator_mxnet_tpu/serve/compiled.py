"""CompiledModel — a frozen, bucket-compiled inference callable.

Reference counterpart: ``CachedOp`` in inference mode (``src/imperative/
cached_op.cc``) — capture the graph once, replay it per request. The jit
equivalent adds one production hazard the reference never had: *every new
input shape is a fresh XLA compile*, seconds of latency injected into a
random unlucky request. :class:`CompiledModel` closes that hole:

- inputs quantize onto a :class:`~incubator_mxnet_tpu.serve.buckets
  .BucketTable` (powers-of-two padding on the named axes);
- :meth:`warmup` AOT-compiles **every** bucket combination up front
  (``jax.jit(...).lower(...).compile()``), so steady-state traffic never
  traces;
- a hit/miss/compile counter makes the "zero post-warmup recompiles"
  contract *assertable* — a post-warmup compile is a bug (unbucketed shape
  reaching the model), not a silent latency spike;
- input buffers are donated to the executable on accelerator backends
  (requests are one-shot buffers; parameters are not donated).

Two model sources compile identically: a live :class:`gluon.HybridBlock`
(traced through the same inference-mode pure function ``export()``
serializes) and a cold-loaded :class:`gluon.SymbolBlock` artifact (one
fixed-shape StableHLO per bucket, written by :func:`export_for_serving`).
Parameters ride as call arguments, so :meth:`refresh_params` swaps model
versions in place with **zero** recompiles.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import current_context
from ..lockcheck import make_rlock
from ..ndarray import NDArray
from .. import profiler
from .buckets import BucketTable

__all__ = ["CompiledModel", "export_for_serving"]


def _as_numpy(x) -> onp.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class CompiledModel:
    """Bucket-compiled inference over a Block or an exported artifact.

    ``input_axes``: one ``{axis_index: bucket_axis_name}`` dict per array
    input, mapping the dims that get padded (e.g. BERT:
    ``[{0: "batch", 1: "seq"}, {0: "batch", 1: "seq"}, {0: "batch"},
    {0: "batch"}]``). Unmapped dims keep the example signature's size.

    ``output_axes``: same shape per output; default pads every output's
    axis 0 back from the ``"batch"`` bucket (or the table's first axis).

    ``pad_values``: scalar or one scalar per input (e.g. pad
    ``valid_length`` with 0 so attention masks the fake rows).

    ``donate``: ``"auto"`` donates request buffers to XLA on non-CPU
    backends only (CPU does not support donation and would warn per call).
    """

    def __init__(self, block, table: BucketTable,
                 input_axes: Sequence[Dict[int, str]],
                 example_args: Optional[Sequence] = None,
                 output_axes: Optional[Sequence[Dict[int, str]]] = None,
                 pad_values: Any = 0, donate: Any = "auto", ctx=None,
                 autotune_key: Optional[str] = None):
        from ..gluon.block import HybridBlock, SymbolBlock
        self._table = table
        self._input_axes = [dict(a) for a in input_axes]
        self._output_axes = ([dict(a) for a in output_axes]
                             if output_axes is not None else None)
        self._ctx = ctx or current_context()
        self._lock = make_rlock("CompiledModel._lock")
        self._exe: Dict[tuple, Callable] = {}
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "compiles": 0, "warmup_compiles": 0,
            "post_warmup_compiles": 0}
        self._warmed = False
        self._block = block
        # donation *intent* ("auto"/True/False), kept apart from the
        # backend-resolved argnums so mx.analysis.hlo can reason about the
        # accelerator deployment even when staging runs on CPU
        self._donate_requested = donate
        # build-time autotune consult (MXTPU_AUTOTUNE_DIR): a banked
        # winner's env knobs overlay every bucket's trace+compile in
        # _compile — same contract as ShardedTrainer, under the serving
        # ledger site "serve.compiled"
        from .. import autotune as _autotune
        # the resolved key is kept so a derived build (e.g.
        # quantization.quantize_model's int8 twin) can inherit it and
        # keep consulting the same banked winner
        self._autotune_key = autotune_key or type(block).__name__.lower()
        self.autotune_entry = _autotune.consult(
            "serve.compiled", self._autotune_key)
        # in-graph numerics telemetry (MXTPU_NUMERICS, resolved ONCE at
        # build like the autotune consult): when enabled every bucket's
        # executable additionally returns per-site stat vectors —
        # numerics.tap()-tagged activations plus each output tensor
        # (serve.out:<i>) — computed in-graph over the padded bucket
        # tensors; predict() syncs them every cfg.every requests
        from ..telemetry import numerics as _numerics
        self._numerics_cfg = _numerics.config()
        self._num_seen = 0           # predict-call decimation counter

        if isinstance(block, SymbolBlock):
            arch = block._arch
            if not block._sigs:
                raise MXNetError("artifact has no StableHLO graphs; "
                                 "re-export with HybridBlock.export()")
            self._mode = "artifact"
            self._n_in = arch["n_inputs"]
            self._in_avals = [(tuple(s), str(d))
                              for s, d in block._sigs[0]["in_avals"]]
            self._key_impl = arch["key"]["impl"]
            self._key_data = onp.asarray(jax.random.key_data(
                jax.random.key(0, impl=self._key_impl)))
            self._param_order = list(arch["param_order"])
        elif isinstance(block, HybridBlock):
            self._mode = "block"
            if getattr(block, "_last_sig", None) is None:
                if example_args is None:
                    raise MXNetError(
                        "CompiledModel over a live block needs either a "
                        "prior hybridized forward or example_args to "
                        "establish the call signature")
                if not block._active:
                    block.hybridize()
                block(*example_args)  # warm-up: deferred init + signature
            skeleton, n_in, in_avals, ctx0 = block._last_sig
            self._skeleton, self._n_in = skeleton, n_in
            self._in_avals = [(tuple(s), str(d)) for s, d in in_avals]
            self._ctx = ctx or ctx0
            from .. import random as random_mod
            self._key_impl = random_mod._impl()
            self._key_data = onp.asarray(jax.random.key_data(
                jax.random.key(0, impl=self._key_impl)))
            self._pure, self._meta = block._make_pure_infer(
                skeleton, n_in, self._ctx)
            if self._numerics_cfg.enabled:
                self._pure = self._wrap_pure_stats(self._pure)
            if donate == "auto":
                donate = jax.default_backend() != "cpu"
            self._jit = jax.jit(
                self._pure,
                donate_argnums=(tuple(range(1, 1 + n_in)) if donate else ()))
        else:
            raise MXNetError(f"CompiledModel cannot wrap {type(block)}; "
                             "pass a HybridBlock or a SymbolBlock artifact")
        if len(self._input_axes) != self._n_in:
            raise MXNetError(
                f"input_axes has {len(self._input_axes)} entries but the "
                f"model takes {self._n_in} array inputs")
        for spec in self._input_axes:
            for name in spec.values():
                if name not in table.axes:
                    raise MXNetError(f"input_axes names bucket axis "
                                     f"{name!r} not in {table!r}")
        for spec, (shape, _d) in zip(self._input_axes, self._in_avals):
            for axis in spec:
                if axis >= len(shape):
                    raise MXNetError(
                        f"input_axes maps axis {axis} but the recorded "
                        f"input has shape {shape}")
        if onp.isscalar(pad_values) or pad_values is None:
            pad_values = [pad_values or 0] * self._n_in
        self._pad_values = list(pad_values)
        if len(self._pad_values) != self._n_in:
            raise MXNetError(
                f"pad_values has {len(self._pad_values)} entries but the "
                f"model takes {self._n_in} array inputs")
        self._primary_axis = ("batch" if "batch" in table.axes
                              else sorted(table.axes)[0])
        self._pvals = None
        self.refresh_params()
        # attribute this model's resident weight buffers on the
        # device-memory ledger (weak provider: an unloaded version
        # drops off the ledger when the registry lets go of it)
        from ..telemetry import memory as _memory
        self._mem_unregister = _memory.register_site(
            "serve.compiled", self._resident_bytes)

    def _resident_bytes(self) -> int:
        """Device bytes this compiled model pins between requests (the
        weight buffers shared by every warmed bucket) — the
        ``serve.compiled`` site of the ``telemetry.memory`` ledger."""
        with self._lock:
            pvals = self._pvals or ()
            return sum(int(getattr(p, "nbytes", 0) or 0) for p in pvals)

    # -- parameters ----------------------------------------------------
    def refresh_params(self) -> None:
        """Re-read parameter values from the wrapped block — the version
        swap path. Shapes must match the compiled graphs, so this never
        recompiles."""
        with self._lock:
            if self._mode == "artifact":
                try:
                    self._pvals = [self._block._param_arrays[n]._data
                                   for n in self._param_order]
                except KeyError as e:
                    raise MXNetError(f"artifact is missing parameter {e}; "
                                     "pass param_file to imports()") from e
            else:
                self._pvals = [p.data(self._ctx)._data
                               for p in self._block._cached_params]

    # -- bucketing ------------------------------------------------------
    def signature_for(self, assignment: Dict[str, int]
                      ) -> List[Tuple[tuple, str]]:
        """Input (shape, dtype) list for one bucket assignment."""
        sig = []
        for (shape, dtype), spec in zip(self._in_avals, self._input_axes):
            s = list(shape)
            for axis, name in spec.items():
                s[axis] = assignment[name]
            sig.append((tuple(s), dtype))
        return sig

    def _sizes_of(self, arrays: Sequence[onp.ndarray]) -> Dict[str, int]:
        sizes: Dict[str, int] = {}
        for a, spec in zip(arrays, self._input_axes):
            for axis, name in spec.items():
                if axis >= a.ndim:
                    raise MXNetError(
                        f"input has rank {a.ndim} but input_axes maps "
                        f"axis {axis}")
                sizes[name] = max(sizes.get(name, 0), a.shape[axis])
        return sizes

    # -- numerics -------------------------------------------------------
    def _wrap_pure_stats(self, base: Callable) -> Callable:
        """Wrap the pure inference function so the SAME compiled
        executable also returns the per-site numerics stats —
        ``numerics.tap()``-tagged activations collected during the
        trace plus one ``serve.out:<i>`` site per output — as a second
        (replicated, scalar-sized) result. One executable per bucket
        still; stats are in-graph reductions, never host callbacks."""
        cfg = self._numerics_cfg

        def pure_stats(key_data, *vals):
            from ..telemetry import numerics as _numerics
            with _numerics.collecting(cfg) as col:
                outs = tuple(base(key_data, *vals))
            stats = dict(zip(col.names, col.values))
            for i, o in enumerate(outs):
                site = f"serve.out:{i}"
                if cfg.wants(site):
                    stats[site] = _numerics.graph_stats(o, cfg)
            return outs, stats

        return pure_stats

    def _maybe_record_numerics(self, stats_dev) -> None:
        """Host half of serve numerics: decimated by request count
        (``cfg.every``), the stat arrays sync and fold into the rings/
        gauges/events exactly like the trainer's."""
        cfg = self._numerics_cfg
        with self._lock:
            self._num_seen += 1
            due = (self._num_seen - 1) % cfg.every == 0
            seen = self._num_seen
        if not due:
            return
        from ..telemetry import numerics as _numerics
        _numerics.record("serve.compiled", seen,
                         jax.device_get(stats_dev), cfg)

    # -- compilation ----------------------------------------------------
    def _compile(self, key: tuple, sig) -> Callable:
        from .. import autotune as _autotune
        t0 = time.perf_counter()
        avals = [jax.ShapeDtypeStruct(self._key_data.shape,
                                      self._key_data.dtype)]
        avals += [jax.ShapeDtypeStruct(s, jnp.dtype(d)) for s, d in sig]
        avals += [jax.ShapeDtypeStruct(p.shape, p.dtype)
                  for p in self._pvals]
        with _autotune.applied(self.autotune_entry):
            # the trace reads tunable env knobs (flash block sizes,
            # embed-grad path) — the cached winner overlays exactly this
            # scope; an explicitly user-set variable still wins
            if self._mode == "artifact":
                ins = [jax.ShapeDtypeStruct(s, jnp.dtype(d)) for s, d in sig]
                ent = self._block._sig_for(ins)
                call = ent["exported"].call
                if self._numerics_cfg.enabled:
                    # baked StableHLO has no taps left; output-site
                    # stats still compute in-graph around the call
                    call = self._wrap_pure_stats(call)
                fn = jax.jit(call)
                exe = fn.lower(*avals).compile()
                info = {"out_fmt": ent["out_fmt"], "multi": ent["multi"]}
            else:
                exe = self._jit.lower(*avals).compile()
                info = {"out_fmt": self._meta["out_fmt"],
                        "multi": self._meta["multi"]}
        self._exe[key] = (exe, info)
        self.stats["compiles"] += 1
        if self._warmed:
            self.stats["post_warmup_compiles"] += 1
        else:
            self.stats["warmup_compiles"] += 1
        # process-wide recompile ledger: a post-warmup entry here is the
        # "unbucketed shape reached the model" bug, assertable anywhere
        from ..telemetry import compile_log
        compile_log.note("serve.compiled", sig,
                         wall_ms=(time.perf_counter() - t0) * 1e3,
                         warmup=not self._warmed)
        # bank the bucket's collective-schedule fingerprint (one extra
        # trace, no compile; off = one env read) — replicated serving
        # fleets crosscheck these the same way trainer pods do
        from ..telemetry import collective_ledger as _cledger
        if _cledger.enabled():
            try:
                fn = (jax.jit(call) if self._mode == "artifact"
                      else self._jit)
                _cledger.bank_closed("serve.compiled",
                                     jax.make_jaxpr(fn)(*avals), sig)
            except Exception:  # noqa: BLE001 — never break a compile
                pass
        return self._exe[key]

    def warmup(self, verbose: bool = False) -> Dict[str, Any]:
        """AOT-compile every bucket combination; returns a summary dict
        (bucket count, compile seconds). After warmup any further compile
        increments ``stats['post_warmup_compiles']`` — the counter the
        zero-recompile serving contract asserts on."""
        t0 = time.perf_counter()
        n = 0
        # holding the model lock across the AOT compiles is the warmup
        # CONTRACT: predict() callers block until every bucket is ready
        # instead of racing half a table
        with self._lock:  # mxlint: disable=MX803
            for assignment in self._table.assignments():
                sig = self.signature_for(assignment)
                key = tuple(sig)
                if key not in self._exe:
                    with profiler.Scope("serve.compile"):
                        self._compile(key, sig)
                    n += 1
                    if verbose:
                        print(f"serve: compiled bucket {assignment}")
            self._warmed = True
        return {"buckets": self._table.num_buckets(), "compiled": n,
                "seconds": round(time.perf_counter() - t0, 3)}

    def cache_info(self) -> Dict[str, int]:
        """Copy of the compile-cache counters plus cache size."""
        with self._lock:
            info = dict(self.stats)
            info["cached_executables"] = len(self._exe)
            info["warmed_up"] = self._warmed
        return info

    # -- inference ------------------------------------------------------
    def _pad(self, arrays: List[onp.ndarray],
             assignment: Dict[str, int]) -> List[onp.ndarray]:
        out = []
        for a, spec, pv, (shape, dtype) in zip(
                arrays, self._input_axes, self._pad_values, self._in_avals):
            target = list(a.shape)
            for axis, name in spec.items():
                target[axis] = assignment[name]
            a = a.astype(dtype, copy=False)
            if tuple(target) != a.shape:
                widths = [(0, t - s) for s, t in zip(a.shape, target)]
                a = onp.pad(a, widths, mode="constant", constant_values=pv)
            out.append(a)
        return out

    def predict(self, *args):
        """Run one padded, compiled inference call; padding is sliced back
        off every output so callers never see bucket geometry. Accepts
        NDArray / numpy / nested-list inputs; returns NDArray(s).

        The whole call is one ``serve.predict`` profiler frame with
        ``serve.pad`` / ``serve.compute`` / ``serve.unpad`` child spans,
        so ``profiler.step_report(frame="serve.predict")`` attributes
        the serving host gap the same way the trainer's ``step`` frame
        does for training."""
        with profiler.Frame("serve.predict"):
            with profiler.Scope("serve.pad"):
                arrays = [_as_numpy(a) for a in args]
                if len(arrays) != self._n_in:
                    raise MXNetError(f"expected {self._n_in} inputs, "
                                     f"got {len(arrays)}")
                sizes = self._sizes_of(arrays)
                assignment = self._table.assignment(sizes)
                sig = self.signature_for(assignment)
                key = tuple(sig)
                padded = self._pad(arrays, assignment)
            # a cold-bucket compile intentionally blocks peers: two
            # threads racing the same missing bucket must produce ONE
            # executable, not two XLA compiles
            with self._lock:  # mxlint: disable=MX803
                hit = key in self._exe
                if hit:
                    self.stats["hits"] += 1
                    exe, info = self._exe[key]
                else:
                    self.stats["misses"] += 1
                    # a cold-bucket compile is seconds of host work — give
                    # it its own segment so step_report shows "compile",
                    # not an inflated python remainder / host gap
                    with profiler.Scope("serve.compile"):
                        exe, info = self._compile(key, sig)
                pvals = self._pvals
            # a RESOURCE_EXHAUSTED out of the compiled call writes ONE
            # OOM flight bundle (live ledger + static peaks), re-raised
            from ..telemetry import memory as _memory
            with profiler.Scope("serve.compute"), \
                    _memory.oom_guard("serve.compiled"):
                outs = exe(self._key_data, *padded, *pvals)
            if self._numerics_cfg.enabled:
                outs, stats_dev = outs
                self._maybe_record_numerics(stats_dev)
            with profiler.Scope("serve.unpad"):
                result = self._unpad(list(outs), info, sizes)
            return result

    __call__ = predict

    def _unpad(self, flat: List[jax.Array], info, sizes: Dict[str, int]):
        out_axes = self._output_axes
        if out_axes is None:
            out_axes = [{0: self._primary_axis}] * len(flat)
        if len(out_axes) != len(flat):
            raise MXNetError(
                f"output_axes has {len(out_axes)} entries but the model "
                f"returned {len(flat)} outputs")
        nds = []
        for o, spec in zip(flat, out_axes):
            slicer = [slice(None)] * o.ndim
            changed = False
            for axis, name in spec.items():
                if axis < o.ndim and name in sizes \
                        and o.shape[axis] != sizes[name]:
                    slicer[axis] = slice(0, sizes[name])
                    changed = True
            nds.append(NDArray(o[tuple(slicer)] if changed else o,
                               ctx=self._ctx))
        fmt = info["out_fmt"]
        from ..gluon.block import _regroup
        result = _regroup(nds, fmt)
        return tuple(result) if info["multi"] else result[0]


def export_for_serving(block, path: str, table: BucketTable,
                       input_axes: Sequence[Dict[int, str]],
                       epoch: int = 0, platforms=None) -> Tuple[str, str]:
    """Export one StableHLO graph per bucket combination so the artifact
    can be cold-loaded (``SymbolBlock.imports``) and served with zero
    recompiles — the deploy-side half of :class:`CompiledModel`.

    ``block`` must be hybridized with one forward call recorded (the same
    contract as :meth:`HybridBlock.export`); the bucketed axes of that
    recorded signature are replaced by every bucket assignment.
    """
    if getattr(block, "_last_sig", None) is None:
        raise MXNetError("export_for_serving needs a traced graph: call "
                         "hybridize() and run one forward first")
    _, n_in, in_avals, _ = block._last_sig
    if len(input_axes) != n_in:
        raise MXNetError(f"input_axes has {len(input_axes)} entries but "
                         f"the model takes {n_in} array inputs")
    signatures = []
    for assignment in table.assignments():
        sig = []
        for (shape, dtype), spec in zip(in_avals, input_axes):
            s = list(shape)
            for axis, name in spec.items():
                s[axis] = assignment[name]
            sig.append((tuple(s), dtype))
        signatures.append(sig)
    return block.export(path, epoch=epoch, platforms=platforms,
                        signatures=signatures)

"""On-disk AOT artifact cache — restart-time prewarm without retracing.

Reference counterpart: TVM's ahead-of-time deployment story
(arXiv:1802.04799) — compile once, persist the artifact, and recovery is
a file load, not a recompile. On this runtime the artifact is the
``export_for_serving`` bundle (StableHLO graph per bucket signature +
``.params`` weights + manifest), so a restarted replica rebuilds its
:class:`~incubator_mxnet_tpu.serve.compiled.CompiledModel` from the
cache's :class:`~incubator_mxnet_tpu.gluon.block.SymbolBlock` path — no
Python-model retrace, and the telemetry compile ledger can prove the
restore added **zero** post-warmup compiles.

Integrity discipline mirrors ``fault.checkpoint``: every cached file's
CRC32 lands in a ``manifest.json``, writes go to a same-filesystem temp
directory finalized by one atomic ``os.replace``, and :meth:`get`
verifies every checksum before handing the artifact out — a corrupt
entry (bit rot, truncated write, or the seeded ``corrupt_artifact``
chaos injection) is **evicted and reported as a miss**, never served.

Cache key: ``(model, version, bucket signature, jax version)`` — the
bucket signature digests the table ladders + input-axis mapping, and the
jax version pins StableHLO compatibility, so an upgraded fleet never
deserializes a stale graph. Every lookup publishes a ``serve.prewarm``
event (outcome ``hit`` / ``miss`` / ``corrupt``) and bumps
``mxtpu_serve_prewarm_total{outcome=...}``.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import zlib
from typing import Dict, Optional, Sequence, Tuple

import jax

from ..base import MXNetError
from ..fault import inject
from ..lockcheck import make_lock
from .buckets import BucketTable

__all__ = ["ArtifactCache", "ArtifactCorruptError", "signature_key"]

MANIFEST_FILE = "manifest.json"
_PREFIX = "art"          # files inside an entry: art-symbol.json, ...
_TMP_PREFIX = ".tmp-"


class ArtifactCorruptError(MXNetError):
    """A cached artifact exists but fails CRC/manifest verification."""


def signature_key(table: BucketTable,
                  input_axes: Sequence[Dict[int, str]]) -> str:
    """Digest of the bucket geometry an artifact was exported for: the
    table's named ladders plus the per-input axis mapping, and the jax
    version (StableHLO artifacts are not stable across major bumps)."""
    doc = {
        "ladders": {name: table.sizes(name) for name in sorted(table.axes)},
        "input_axes": [sorted((int(a), n) for a, n in spec.items())
                       for spec in input_axes],
        "jax": jax.__version__,
    }
    return hashlib.sha1(
        json.dumps(doc, sort_keys=True).encode("utf-8")).hexdigest()[:16]


def _crc_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


class ArtifactCache:
    """Directory of verified ``export_for_serving`` bundles.

    Layout (one directory per entry; the manifest is written last inside
    the temp dir, so a finalized entry always carries its checksums)::

        <root>/<model>/v<version>-<sigkey>/
          manifest.json          # files + CRC32s, input names, key doc
          art-symbol.json        # the export bundle, under one prefix
          art-0000.params
          art-symbol.stablehlo
          art-symbol.1.stablehlo ...
    """

    def __init__(self, root: str):
        self.root = root
        self._lock = make_lock("ArtifactCache._lock")
        self.stats = {"hits": 0, "misses": 0, "corrupt": 0, "puts": 0}

    # -- key / paths -----------------------------------------------------
    def entry_dir(self, model: str, version: int, sigkey: str) -> str:
        return os.path.join(self.root, model, f"v{int(version)}-{sigkey}")

    def _note(self, outcome: str, model: str, version: int,
              sigkey: str, **fields) -> None:
        key = {"hit": "hits", "miss": "misses", "corrupt": "corrupt",
               "put": "puts"}[outcome]
        with self._lock:
            self.stats[key] += 1
        from ..telemetry import events as _tele
        from ..telemetry import metrics as _tmetrics
        _tele.emit("serve.prewarm",
                   severity="warning" if outcome == "corrupt" else "info",
                   model=model, version=version, outcome=outcome,
                   sigkey=sigkey, **fields)
        _tmetrics.counter("mxtpu_serve_prewarm_total",
                          "Artifact-cache prewarm lookups by outcome",
                          model=model, outcome=outcome).inc()

    # -- write path ------------------------------------------------------
    def put(self, model: str, version: int, block, table: BucketTable,
            input_axes: Sequence[Dict[int, str]],
            input_names: Optional[Sequence[str]] = None) -> str:
        """Export ``block`` (hybridized, one forward recorded) for every
        bucket signature into the cache; returns the artifact prefix to
        load from. Atomic: the entry appears complete or not at all, and
        re-putting an existing key replaces it."""
        from .compiled import export_for_serving
        sigkey = signature_key(table, input_axes)
        final = self.entry_dir(model, version, sigkey)
        # pid+thread id: two restarter THREADS repairing the same evicted
        # key must not rmtree each other's half-written export
        tmp = os.path.join(os.path.dirname(final),
                           f"{_TMP_PREFIX}{os.path.basename(final)}-"
                           f"{os.getpid()}-{threading.get_ident()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            prefix = os.path.join(tmp, _PREFIX)
            export_for_serving(block, prefix, table, input_axes)
            files = sorted(n for n in os.listdir(tmp)
                           if n != MANIFEST_FILE)
            manifest = {
                "model": model, "version": int(version), "sigkey": sigkey,
                "jax": jax.__version__,
                "input_names": list(input_names or ["data"]),
                "files": {n: _crc_file(os.path.join(tmp, n))
                          for n in files},
            }
            mpath = os.path.join(tmp, MANIFEST_FILE)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            if os.path.isdir(final):
                shutil.rmtree(final)
            try:
                os.replace(tmp, final)
            except OSError:
                if not os.path.isdir(final):
                    raise
                # a concurrent put of the same key won the rename
                # (ENOTEMPTY onto its fresh entry) — both exports came
                # from the same source, so the winner's copy serves
                shutil.rmtree(tmp, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._note("put", model, version, sigkey, files=len(files))
        return os.path.join(final, _PREFIX)

    # -- read path -------------------------------------------------------
    def get(self, model: str, version: int, table: BucketTable,
            input_axes: Sequence[Dict[int, str]]
            ) -> Optional[Tuple[str, Dict]]:
        """Verified lookup → ``(artifact_prefix, manifest)`` on a hit,
        ``None`` on a miss. A corrupt entry (checksum/manifest mismatch —
        including one injected by the ``corrupt_artifact`` chaos site) is
        evicted and reported as a miss, so the caller falls back to the
        source model and repairs the cache with :meth:`put`."""
        sigkey = signature_key(table, input_axes)
        entry = self.entry_dir(model, version, sigkey)
        mpath = os.path.join(entry, MANIFEST_FILE)
        if not os.path.isfile(mpath):
            self._note("miss", model, version, sigkey)
            return None
        if inject.armed("corrupt_artifact") \
                or inject.should("corrupt_artifact"):
            self._bitflip(entry)
        try:
            manifest = self._verify(entry, mpath)
        except (ArtifactCorruptError, OSError) as e:
            # OSError covers a concurrent eviction/replace racing this
            # verify (files vanishing mid-CRC) — a miss, not a crash
            self._note("corrupt", model, version, sigkey, error=str(e)[:200])
            shutil.rmtree(entry, ignore_errors=True)
            return None
        self._note("hit", model, version, sigkey,
                   files=len(manifest["files"]))
        return os.path.join(entry, _PREFIX), manifest

    def _verify(self, entry: str, mpath: str) -> Dict:
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise ArtifactCorruptError(
                f"{mpath}: unreadable manifest: {e}") from e
        declared = manifest.get("files", {})
        present = {n for n in os.listdir(entry) if n != MANIFEST_FILE}
        if set(declared) != present:
            raise ArtifactCorruptError(
                f"{entry}: manifest declares {sorted(declared)} but entry "
                f"holds {sorted(present)}")
        for name, crc in declared.items():
            got = _crc_file(os.path.join(entry, name))
            if got != crc:
                raise ArtifactCorruptError(
                    f"{entry}: checksum mismatch for {name!r} "
                    f"(manifest {crc}, file {got})")
        return manifest

    @staticmethod
    def _bitflip(entry: str) -> None:
        """Apply the ``corrupt_artifact`` chaos fault: flip one byte of
        the largest cached file (the weights, in practice) on disk, the
        same damage a torn write or bit rot would do."""
        files = [os.path.join(entry, n) for n in os.listdir(entry)
                 if n != MANIFEST_FILE]
        if not files:
            return
        try:
            victim = max(files, key=os.path.getsize)
            size = os.path.getsize(victim)
            if size == 0:
                return
            with open(victim, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
        except OSError:
            pass  # chaos is best-effort; a racing eviction wins

    def snapshot(self) -> Dict:
        with self._lock:
            return dict(self.stats)
